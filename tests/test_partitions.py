"""Partitioned cluster mode (ISSUE 15): partition-local replica groups.

Covers the routing plane end to end — the hash partition map and its wire
form (PARTMAP), the native MOVED guard (a stale map can never silently
read/write the wrong node), pt=-addressed per-partition tree reads, the
smart clients and the thin router, partition-scoped overload — and the
headline chaos case: 4 partitions x 2 replicas, one replica killed in
EVERY partition mid-write-storm, each partition reconverging to a
bit-identical per-partition root with zero cross-partition interference
(flight events + METRICS prove the siblings never left live).
"""

import os
import socket
import threading
import time
import uuid

import pytest

from merklekv_tpu.client import (
    ConnectionError as ClientConnectionError,
    MerkleKVClient,
    MerkleKVError,
    MovedError,
    PartitionedClient,
    ProtocolError,
    ServerBusyError,
)
from merklekv_tpu.cluster.node import ClusterNode
from merklekv_tpu.cluster.partmap import (
    PartitionMap,
    PartitionMapError,
    parse_map_spec,
    partition_of,
)
from merklekv_tpu.cluster.transport import TcpBroker
from merklekv_tpu.config import Config
from merklekv_tpu.native_bindings import NativeEngine, NativeServer
from merklekv_tpu.obs.flightrec import get_recorder


def wait_for(fn, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def free_ports(n: int) -> list[int]:
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def keys_for(pid: int, count: int, n: int, tag: str = "k") -> list[str]:
    """Deterministic keys hashing to partition ``pid`` of ``count``."""
    out, i = [], 0
    while len(out) < n:
        k = f"{tag}:{i:06d}"
        if partition_of(k, count) == pid:
            out.append(k)
        i += 1
    return out


# ------------------------------------------------------------- unit layer


def test_partition_of_stable_and_range():
    # Golden stability: the function is a wire contract (native guard,
    # clients, router, bench drivers all route with it) — a change here
    # remaps every deployed keyspace.
    assert partition_of(b"key:000000", 4) == partition_of("key:000000", 4)
    for count in (1, 2, 4, 16):
        seen = {partition_of(f"k{i}", count) for i in range(400)}
        assert seen <= set(range(count))
        if count <= 4:
            assert seen == set(range(count))  # every partition reachable
    with pytest.raises(ValueError):
        partition_of("k", 0)


def test_partition_of_matches_native_guard():
    """Python routing and the native dispatch guard MUST agree key by key
    — disagreement turns every write into a MOVED ping-pong."""
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0)
    srv.set_partition(3, 4, 1)
    srv.start()
    try:
        with MerkleKVClient("127.0.0.1", srv.port) as c:
            for i in range(64):
                k = f"agree:{i}"
                pid = partition_of(k, 4)
                if pid == 1:
                    assert c.set(k, "v")
                else:
                    with pytest.raises(MovedError) as ei:
                        c.set(k, "v")
                    assert ei.value.partition == pid
                    assert ei.value.epoch == 3
    finally:
        srv.close()
        eng.close()


def test_partmap_wire_roundtrip_and_validation():
    m = PartitionMap(
        epoch=7,
        replicas=[["h:1", "h:2"], ["h:3"], ["h:4", "h:5"]],
    ).validate()
    wire = m.wire()
    lines = wire.split("\r\n")
    assert lines[0] == "PARTMAP 7 3"
    assert lines[-2] == "END"
    parsed = PartitionMap.from_wire(lines[0], lines[1:-2])
    assert parsed == m
    # Every malformation raises, never a partial map.
    bad = [
        ("PARTMAP 7", lines[1:-2]),            # short header
        ("PARTMAP x 3", lines[1:-2]),          # non-numeric epoch
        ("PARTMAP 0 3", lines[1:-2]),          # epoch < 1
        ("PARTMAP 7 3", lines[1:3]),           # missing row
        ("PARTMAP 7 3", lines[1:3] + ["9 h:1"]),   # pid out of range
        ("PARTMAP 7 3", lines[1:3] + [lines[1]]),  # duplicate pid
        ("PARTMAP 7 3", lines[1:3] + ["2"]),       # row without replicas
        ("PARTMAP 7 3", lines[1:3] + ["2 nohostport"]),
        ("PARTMAP 7 3", lines[1:3] + ["2 h:notaport"]),
    ]
    for header, rows in bad:
        with pytest.raises(PartitionMapError):
            PartitionMap.from_wire(header, rows)


def test_parse_map_spec_validation():
    m = parse_map_spec("0=a:1,b:2;1=c:3", 2, epoch=4)
    assert m.epoch == 4 and m.count == 2
    assert m.replicas[0] == ["a:1", "b:2"]
    for spec, count in [
        ("0=a:1", 2),              # missing partition 1
        ("0=a:1;0=b:2", 1),        # duplicate group
        ("2=a:1;0=b:2", 2),        # pid out of range
        ("0=", 1),                 # no replicas
        ("0=a", 1),                # not host:port
        ("nonsense", 1),           # no '='
    ]:
        with pytest.raises(PartitionMapError):
            parse_map_spec(spec, count)


def test_cluster_config_validation():
    base = {
        "cluster": {
            "partitions": 2,
            "partition_id": 0,
            "partition_map": "0=a:1;1=b:2",
        }
    }
    cfg = Config.from_dict(base)
    assert cfg.cluster.partitions == 2
    for mutation in [
        {"partition_id": 5},
        {"partition_id": -1},
        {"partition_map": ""},
        {"partition_map": "0=a:1"},  # incomplete coverage
        {"map_epoch": 0},
        {"partitions": -1},
    ]:
        raw = {"cluster": dict(base["cluster"], **mutation)}
        with pytest.raises(ValueError):
            Config.from_dict(raw)
    # Unpartitioned configs ignore the id/map entirely.
    assert Config.from_dict({}).cluster.partitions == 0


def test_cluster_node_validates_programmatic_partition_config():
    """Review finding (round 2): a programmatically built Config bypasses
    Config.from_dict, and the default partition_id=-1 would make the node
    enforce partition 0 while deriving peers from replicas[-1] — the
    constructor must refuse loudly."""
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0)
    try:
        cfg = Config()
        cfg.cluster.partitions = 2
        cfg.cluster.partition_map = "0=a:1;1=b:2"
        # partition_id left at the -1 default
        with pytest.raises(ValueError, match="partition_id"):
            ClusterNode(cfg, eng, srv)
    finally:
        srv.close()
        eng.close()


def test_shrunk_map_surfaces_moved_not_indexerror():
    """Review finding (round 2): a map refresh that SHRINKS the partition
    count mid-operation must surface the typed MovedError (healable by
    the retry loop), never a raw IndexError."""
    pc = PartitionedClient(["127.0.0.1:1"])  # never connected
    pc._map = PartitionMap(epoch=3, replicas=[["a:1"], ["b:2"]]).validate()
    with pytest.raises(MovedError) as ei:
        pc._client(5)
    assert ei.value.partition == 5 and ei.value.epoch == 3


def test_moved_error_typed_and_retry_classification():
    from merklekv_tpu.cluster.retry import (
        ROUTED_RETRYABLE_ERRORS,
        RETRYABLE_ERRORS,
    )

    assert MovedError in ROUTED_RETRYABLE_ERRORS
    # A plain caller has no map to refresh: retrying the same node would
    # collect the same refusal, so generic retries exclude it.
    assert MovedError not in RETRYABLE_ERRORS
    assert issubclass(MovedError, ProtocolError)


# ------------------------------------------------------- native guard layer


@pytest.fixture
def guarded_server():
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0)
    srv.set_partition(5, 4, 2)
    srv.start()
    yield eng, srv
    srv.close()
    eng.close()


def test_native_guard_every_key_verb(guarded_server):
    eng, srv = guarded_server
    own = keys_for(2, 4, 4, "g")
    foreign = keys_for(1, 4, 2, "g")
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        assert c.set(own[0], "v")
        assert c.get(own[0]) == "v"
        for op in (
            lambda: c.set(foreign[0], "v"),
            lambda: c.get(foreign[0]),
            lambda: c.delete(foreign[0]),
            lambda: c.increment(foreign[0]),
            lambda: c.append(foreign[0], "x"),
            lambda: c.mget([own[0], foreign[0]]),
            lambda: c.mset({own[1]: "v", foreign[1]: "v"}),
            lambda: c.exists(own[0], foreign[0]),
        ):
            with pytest.raises(MovedError) as ei:
                op()
            assert ei.value.partition == 1
            assert ei.value.epoch == 5
        # The foreign keys never landed (MSET refused whole).
        assert eng.get(foreign[1].encode()) is None
        # Keyless verbs and the management plane stay open.
        assert c.ping().startswith("PONG")
        assert c.dbsize() >= 1
        stats = c.stats()
        assert int(stats["moved_commands"]) >= 8
        assert stats["partition_id"] == "2"
        assert stats["partition_count"] == "4"
        assert stats["partition_epoch"] == "5"


def test_pt_addressing_hash_and_treelevel(guarded_server):
    eng, srv = guarded_server
    eng.set(keys_for(2, 4, 1, "pt")[0].encode(), b"v")
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        c.partition_id = 2
        root = c.hash()
        assert len(bytes.fromhex(root)) == 32
        rows, n = c.tree_level(0, 0, 0)
        assert n >= 1
        c.partition_id = 3  # stale map: this node no longer serves 3
        with pytest.raises(MovedError) as ei:
            c.hash()
        assert ei.value.partition == 3
        with pytest.raises(MovedError):
            c.tree_level(0, 0, 0)


def test_pt_token_ignored_on_unpartitioned_node():
    # Degenerate single-group deployment: an unpartitioned node serves its
    # whole keyspace regardless of the address (count 0 = guard off).
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0)
    srv.start()
    try:
        eng.set(b"u", b"v")
        with MerkleKVClient("127.0.0.1", srv.port) as c:
            c.partition_id = 3
            assert len(bytes.fromhex(c.hash())) == 32
            _, n = c.tree_level(0, 0, 0)
            assert n == 1
    finally:
        srv.close()
        eng.close()


# ------------------------------------------------------- PARTMAP wire fuzz


class _CannedServer:
    """One-shot server: accept a connection, read one line, answer the
    canned bytes, close — the hostile-donor rig for wire fuzzing."""

    def __init__(self, payload: bytes) -> None:
        self._payload = payload
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(4)
        self.port = self._sock.getsockname()[1]
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        try:
            conn, _ = self._sock.accept()
            conn.settimeout(5)
            try:
                conn.recv(4096)  # the PARTMAP request line
                conn.sendall(self._payload)
            finally:
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                conn.close()
        except OSError:
            pass

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=5)


def _fetch_map_from_canned(payload: bytes):
    srv = _CannedServer(payload)
    try:
        with MerkleKVClient("127.0.0.1", srv.port, timeout=2.0) as c:
            return c.partition_map()
    finally:
        srv.close()


def test_partmap_fuzz_truncation_every_offset():
    """A PARTMAP reply cut at EVERY byte offset either parses as a fully
    valid map (cut past the END) or raises a clean typed error — never a
    partial map, never a hang, never a non-client exception."""
    good = (
        "PARTMAP 3 2\r\n"
        "0 127.0.0.1:7001 127.0.0.1:7002\r\n"
        "1 127.0.0.1:7003 127.0.0.1:7004\r\n"
        "END\r\n"
    ).encode()
    full_len = len(good)
    for cut in range(full_len + 1):
        try:
            m = _fetch_map_from_canned(good[:cut])
        except (MerkleKVError, PartitionMapError):
            continue  # clean refusal (ProtocolError/ConnectionError/...)
        assert cut >= full_len - 2, f"partial map accepted at cut={cut}"
        assert m.count == 2 and m.epoch == 3
        assert m.replicas[1] == ["127.0.0.1:7003", "127.0.0.1:7004"]


def test_partmap_fuzz_seeded_byte_flips():
    """48 seeded single-byte corruptions: every outcome is either a clean
    typed error or a STILL-VALID map object (a flipped digit inside a
    port number is indistinguishable from a legitimate map — but it must
    parse/validate as one, never crash or half-parse)."""
    import random

    good = (
        "PARTMAP 3 2\r\n"
        "0 127.0.0.1:7001 127.0.0.1:7002\r\n"
        "1 127.0.0.1:7003 127.0.0.1:7004\r\n"
        "END\r\n"
    ).encode()
    rng = random.Random(1504)
    for _ in range(48):
        pos = rng.randrange(len(good))
        flip = bytes([good[pos] ^ (1 << rng.randrange(8))])
        payload = good[:pos] + flip + good[pos + 1:]
        try:
            m = _fetch_map_from_canned(payload)
        except (MerkleKVError, PartitionMapError):
            continue
        m.validate()  # whatever came back is a complete, coherent map
        assert m.count == len(m.replicas)


# --------------------------------------------------- in-process clusters


class PartCluster:
    """P partitions x R replicas of in-process ClusterNodes on fixed
    ports, replicating per partition over one shared broker."""

    def __init__(
        self,
        partitions: int,
        replicas: int,
        anti_entropy: bool = False,
        env_for=None,  # optional {(pid, r): {ENV: val}} during start
    ) -> None:
        self.partitions = partitions
        self.replicas = replicas
        self.broker = TcpBroker()
        self.topic = f"part-{uuid.uuid4().hex[:8]}"
        ports = free_ports(partitions * replicas)
        self.addr = [
            [
                f"127.0.0.1:{ports[p * replicas + r]}"
                for r in range(replicas)
            ]
            for p in range(partitions)
        ]
        self.spec = ";".join(
            f"{p}=" + ",".join(self.addr[p]) for p in range(partitions)
        )
        self.engines: dict[tuple[int, int], NativeEngine] = {}
        self.servers: dict[tuple[int, int], NativeServer] = {}
        self.nodes: dict[tuple[int, int], ClusterNode] = {}
        self._anti_entropy = anti_entropy
        for p in range(partitions):
            for r in range(replicas):
                overrides = (env_for or {}).get((p, r), {})
                saved = {k: os.environ.get(k) for k in overrides}
                os.environ.update(overrides)
                try:
                    self.start_node(p, r)
                finally:
                    for k, v in saved.items():
                        if v is None:
                            os.environ.pop(k, None)
                        else:
                            os.environ[k] = v

    def _cfg(self, pid: int, r: int) -> Config:
        cfg = Config()
        cfg.host = "127.0.0.1"
        cfg.port = int(self.addr[pid][r].rsplit(":", 1)[1])
        cfg.cluster.partitions = self.partitions
        cfg.cluster.partition_id = pid
        cfg.cluster.partition_map = self.spec
        cfg.replication.enabled = True
        cfg.replication.mqtt_broker = self.broker.host
        cfg.replication.mqtt_port = self.broker.port
        cfg.replication.topic_prefix = self.topic
        cfg.anti_entropy.enabled = self._anti_entropy
        cfg.anti_entropy.engine = "cpu"  # no device mirror in tests
        cfg.anti_entropy.interval_seconds = 3600.0  # manual sync only
        return cfg

    def start_node(
        self, pid: int, r: int, reuse_engine: bool = True
    ) -> ClusterNode:
        key = (pid, r)
        eng = self.engines.get(key) if reuse_engine else None
        if eng is None:
            eng = NativeEngine("mem")
            self.engines[key] = eng
        port = int(self.addr[pid][r].rsplit(":", 1)[1])
        srv = NativeServer(eng, "127.0.0.1", port)
        srv.start()
        self.servers[key] = srv
        node = ClusterNode(self._cfg(pid, r), eng, srv)
        node.start()
        self.nodes[key] = node
        return node

    def kill(self, pid: int, r: int) -> None:
        """Abrupt replica death, as observable from the wire: the
        listener and every established connection die FIRST (clients see
        resets, like a crashed process), then the in-process control
        plane is reaped and the native object freed — the drain threads
        must not race a destroyed server (a real SIGKILL takes them out
        atomically; test_partition_chaos_proc.py covers that shape). The
        engine object survives only as the restart seed (warm rejoin)."""
        key = (pid, r)
        srv = self.servers.pop(key)
        srv.stop()  # connections reset NOW — the death the storm sees
        node = self.nodes.pop(key)
        try:
            node.stop()
        except Exception:
            pass  # a dead server mid-teardown is the point
        srv.close()

    def root(self, pid: int, r: int) -> bytes:
        return self.engines[(pid, r)].merkle_root() or b""

    def close(self) -> None:
        for key in list(self.nodes):
            try:
                self.nodes[key].stop()
            except Exception:
                pass
        for srv in self.servers.values():
            srv.close()
        for eng in self.engines.values():
            eng.close()
        self.broker.close()


# ------------------------------------------------------- smart client layer


def test_partitioned_client_routes_and_isolates():
    cluster = PartCluster(2, 1)
    try:
        seeds = [cluster.addr[0][0]]
        with PartitionedClient(seeds) as pc:
            assert pc.map.count == 2
            kv = {f"r:{i:04d}": f"v{i}" for i in range(60)}
            for k, v in kv.items():
                pc.set(k, v)
            assert all(pc.get(k) == v for k, v in kv.items())
            got = pc.mget(list(kv))
            assert got == kv
            assert pc.exists(*list(kv)[:10]) == 10
            pc.mset({"m:1": "a", "m:2": "b"})
            assert pc.get("m:1") == "a"
            # Partition purity: every engine holds ONLY its own keys.
            for k in kv:
                pid = partition_of(k, 2)
                assert cluster.engines[(pid, 0)].get(k.encode()) is not None
                assert cluster.engines[(1 - pid, 0)].get(k.encode()) is None
            # Per-partition roots resolve (pt=-addressed), and differ.
            roots = pc.partition_roots()
            assert set(roots) == {0, 1} and roots[0] != roots[1]
    finally:
        cluster.close()


def test_stale_map_never_a_silent_wrong_node_read():
    """The stale-map safety headline: a client routing partition 1's keys
    at partition 0's node gets MOVED -> refresh -> re-route, and the key
    lands ONLY on the right node. Without the guard this is a silent
    wrong-node write followed by a silent empty read."""
    cluster = PartCluster(2, 1)
    try:
        pc = PartitionedClient([cluster.addr[0][0]]).connect()
        # Doctor the map: both partitions allegedly live on node 0.
        pc._map = PartitionMap(
            epoch=1,
            replicas=[[cluster.addr[0][0]], [cluster.addr[0][0]]],
        ).validate()
        k1 = keys_for(1, 2, 1, "stale")[0]
        pc.set(k1, "routed-right")  # MOVED -> refresh -> correct node
        assert pc.map.replicas == cluster.nodes[(0, 0)]._partmap.replicas
        assert cluster.engines[(1, 0)].get(k1.encode()) == b"routed-right"
        assert cluster.engines[(0, 0)].get(k1.encode()) is None
        assert pc.get(k1) == "routed-right"
        pc.close()
        # A DUMB client with the same stale idea gets the typed refusal —
        # never a silent NOT_FOUND from the wrong node's keyspace.
        host, _, port = cluster.addr[0][0].rpartition(":")
        with MerkleKVClient(host, int(port)) as c:
            with pytest.raises(MovedError):
                c.get(k1)
    finally:
        cluster.close()


def test_async_partitioned_client_parity():
    import asyncio

    from merklekv_tpu.client import AsyncPartitionedClient

    cluster = PartCluster(2, 1)
    try:
        async def drive():
            async with AsyncPartitionedClient(
                [cluster.addr[1][0]]
            ) as pc:
                for i in range(20):
                    await pc.set(f"a:{i}", f"v{i}")
                vals = [await pc.get(f"a:{i}") for i in range(20)]
                assert vals == [f"v{i}" for i in range(20)]
                # Stale map heals in the async client too.
                pc._map = PartitionMap(
                    epoch=1,
                    replicas=[[cluster.addr[0][0]], [cluster.addr[0][0]]],
                ).validate()
                k1 = keys_for(1, 2, 1, "astale")[0]
                await pc.set(k1, "ok")
                assert (await pc.get(k1)) == "ok"
                roots = {
                    p: await pc.partition_root(p) for p in range(2)
                }
                assert len(roots) == 2
            assert cluster.engines[(1, 0)].get(k1.encode()) == b"ok"

        asyncio.run(drive())
    finally:
        cluster.close()


# --------------------------------------------------------------- router


def test_router_routes_dumb_clients():
    from merklekv_tpu.cluster.router import PartitionRouter

    cluster = PartCluster(2, 1)
    router = None
    try:
        router = PartitionRouter(
            seeds=[cluster.addr[0][0]]
        ).start()
        with MerkleKVClient("127.0.0.1", router.port) as c:
            kv = {f"rt:{i:03d}": f"v{i}" for i in range(40)}
            for k, v in kv.items():
                assert c.set(k, v)
            assert all(c.get(k) == v for k, v in kv.items())
            assert c.mget(list(kv)) == kv
            c.mset({"rm:1": "x", "rm:2": "y"})
            assert c.exists("rm:1", "rm:2", "rt:000") == 3
            assert c.delete("rm:1") is True
            assert c.delete("rm:1") is False
            assert c.increment("rc", 5) == 5
            assert c.dbsize() == len(kv) + 2  # rm:2 + rc
            assert sorted(c.scan("rt:")) == sorted(kv)
            assert c.ping().startswith("PONG")
            m = c.partition_map()
            assert m.count == 2
            # Values with spaces survive the relay byte-exactly.
            c.set("sp", "a b  c")
            assert c.get("sp") == "a b  c"
            # Thin by design: node-local verbs are refused loudly.
            with pytest.raises(ProtocolError, match="router"):
                c.stats()
        # Key placement is partition-pure through the router too.
        for k in kv:
            pid = partition_of(k, 2)
            assert cluster.engines[(pid, 0)].get(k.encode()) is not None
            assert cluster.engines[(1 - pid, 0)].get(k.encode()) is None
    finally:
        if router is not None:
            router.stop()
        cluster.close()


def test_router_heals_stale_map():
    from merklekv_tpu.cluster.router import PartitionRouter

    cluster = PartCluster(2, 1)
    router = None
    try:
        router = PartitionRouter(seeds=[cluster.addr[0][0]]).start()
        # Doctor the router's map (both partitions -> node 0): commands
        # for partition 1 hit MOVED, refresh, and land correctly.
        with router._map_mu:
            router._map = PartitionMap(
                epoch=1,
                replicas=[[cluster.addr[0][0]], [cluster.addr[0][0]]],
            ).validate()
        k1 = keys_for(1, 2, 1, "rtstale")[0]
        with MerkleKVClient("127.0.0.1", router.port) as c:
            assert c.set(k1, "healed")
            assert c.get(k1) == "healed"
        assert cluster.engines[(1, 0)].get(k1.encode()) == b"healed"
        assert cluster.engines[(0, 0)].get(k1.encode()) is None
    finally:
        if router is not None:
            router.stop()
        cluster.close()


# ------------------------------------------- partition-scoped anti-entropy


def test_sync_refuses_cross_partition_peer():
    """A partitioned walk against a peer serving a DIFFERENT partition
    must fail loudly (MOVED surfaces through the sync cycle), never
    'converge' by mirroring a disjoint keyspace as divergence."""
    cluster = PartCluster(2, 1)
    try:
        for pid in range(2):
            for k in keys_for(pid, 2, 30, f"sy{pid}"):
                cluster.engines[(pid, 0)].set(k.encode(), b"v")
        n0 = cluster.nodes[(0, 0)]
        before = cluster.engines[(0, 0)].dbsize()
        host, _, port = cluster.addr[1][0].rpartition(":")
        with pytest.raises(MerkleKVError):
            n0.sync_manager.sync_once(host, int(port))
        # Nothing was repaired-in or mirrored-away.
        assert cluster.engines[(0, 0)].dbsize() == before
    finally:
        cluster.close()


class _ScriptedServer:
    """Per-verb canned responder: serves many requests on one connection,
    answering from a verb -> bytes table (the mid-cycle-lying-peer rig)."""

    def __init__(self, answers: dict[bytes, bytes]) -> None:
        self._answers = answers
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(4)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            try:
                conn.settimeout(5)
                f = conn.makefile("rb")
                while True:
                    raw = f.readline()
                    if not raw:
                        break
                    verb = raw.split()[0].upper() if raw.split() else b""
                    conn.sendall(
                        self._answers.get(verb, b"ERROR Unknown command\r\n")
                    )
            except OSError:
                pass
            finally:
                conn.close()

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=5)


def test_walk_probe_moved_never_degrades_to_paged_scan():
    """Review finding (round 1): a peer whose ownership moved BETWEEN the
    HASH probe and the TREELEVEL probe must abort the cycle — the old
    probe-failure path read the MOVED as 'no TREELEVEL capability' and
    degraded to the paged HASHPAGE/LEAFHASHES scan, verbs the partition
    guard does not cover, against the wrong partition's keyspace."""
    from merklekv_tpu.cluster.sync import SyncManager

    eng = NativeEngine("mem")
    try:
        for k in keys_for(0, 2, 50, "wp"):
            eng.set(k.encode(), b"v")
        before = eng.dbsize()
        # HASH answers a DIFFERENT root (forcing a transfer decision);
        # TREELEVEL answers MOVED (ownership changed mid-cycle); the
        # paged verbs answer too — reaching them is the bug.
        lying = _ScriptedServer({
            b"HASH": b"HASH " + b"a" * 64 + b"\r\n",
            b"TREELEVEL": b"ERROR MOVED 1 2\r\n",
            b"HASHPAGE": b"HASHES 0\r\n",
            b"LEAFHASHES": b"HASHES 0\r\n",
        })
        sm = SyncManager(eng, device="cpu", mode="bisect",
                         partition_id=0)
        try:
            with pytest.raises(MovedError):
                sm.sync_once("127.0.0.1", lying.port)
        finally:
            sm.stop()
            lying.close()
        # Nothing was mirrored away: an empty-paged-scan fallback would
        # have quiet-deleted the whole local keyspace.
        assert eng.dbsize() == before
    finally:
        eng.close()


def test_partition_map_desync_closes_connection():
    """Review finding (round 1): a garbled PARTMAP header leaves an
    unknowable body in flight — the client must CLOSE before raising so
    a caller that catches the error cannot read leftover rows as later
    responses."""
    srv = _ScriptedServer({
        b"PARTMAP": b"PARTMAP 1 bogus\r\n0 h:1\r\nEND\r\n",
        b"PING": b"PONG \r\n",
    })
    try:
        c = MerkleKVClient("127.0.0.1", srv.port, timeout=2).connect()
        with pytest.raises(ProtocolError):
            c.partition_map()
        assert not c.is_connected()
    finally:
        srv.close()


def test_async_client_rotates_on_replica_death():
    """Review finding (round 1): mid-command socket deaths must surface
    as the module's typed ConnectionError in the ASYNC client too, or
    AsyncPartitionedClient's replica rotation never fires."""
    import asyncio

    from merklekv_tpu.client import AsyncPartitionedClient

    cluster = PartCluster(1, 2)
    try:
        async def drive():
            pc = await AsyncPartitionedClient(
                [cluster.addr[0][0]], timeout=5
            ).connect()
            await pc.set("rot:1", "v1")
            # Kill whichever replica the client is talking to.
            used = pc._replica_idx.get(0, 0)
            cluster.kill(0, used)
            # The in-flight connection dies mid-read -> typed
            # ConnectionError -> rotation to the surviving sibling (the
            # value may or may not have replicated before the kill; what
            # must NOT happen is a raw ConnectionResetError escaping).
            assert (await pc.get("rot:1")) in ("v1", None)
            await pc.set("rot:2", "v2")
            assert (await pc.get("rot:2")) == "v2"
            await pc.close()

        asyncio.run(drive())
    finally:
        cluster.close()


# ---------------------------------------------- partition-scoped overload


def test_partition_scoped_overload_busy_isolated():
    """One partition's replica trips MKV_MAX_ENGINE_BYTES: ONLY that
    partition's writes answer BUSY; the sibling partition keeps serving
    with write p99 within 2x its baseline; /healthz reports per-partition
    readiness; the flight ring carries partition_degraded/healed for the
    sick partition only."""
    rec = get_recorder()
    rec.clear()
    cluster = PartCluster(
        2,
        1,
        env_for={(0, 0): {"MKV_MAX_ENGINE_BYTES": "4096"}},
    )
    try:
        p0 = keys_for(0, 2, 200, "ov0")
        p1 = keys_for(1, 2, 200, "ov1")
        h1, _, pt1 = cluster.addr[1][0].rpartition(":")
        h0, _, pt0 = cluster.addr[0][0].rpartition(":")
        c0 = MerkleKVClient(h0, int(pt0)).connect()
        c1 = MerkleKVClient(h1, int(pt1)).connect()
        try:
            # Baseline p99 on the healthy partition.
            base = []
            for k in p1[:100]:
                t0 = time.perf_counter_ns()
                c1.set(k, "x" * 64)
                base.append(time.perf_counter_ns() - t0)
            base.sort()
            base_p99 = base[98]

            # Flood partition 0 past its tiny hard watermark.
            def flooded() -> bool:
                for k in p0:
                    try:
                        c0.set(k, "x" * 256)
                    except (ServerBusyError, ProtocolError):
                        return True
                cluster.nodes[(0, 0)]._overload.poll_once()
                return False

            assert wait_for(flooded, timeout=20)
            assert wait_for(
                lambda: cluster.nodes[(0, 0)]._overload.poll_once() > 0
            )
            # Only partition 0's writes shed; reads stay open there.
            with pytest.raises((ServerBusyError, ProtocolError)):
                c0.set(p0[0], "y")
            assert c0.get(p0[0]) is not None
            # Sibling partition: writes still land, p99 within 2x.
            during = []
            for k in p1[100:]:
                t0 = time.perf_counter_ns()
                c1.set(k, "x" * 64)
                during.append(time.perf_counter_ns() - t0)
            during.sort()
            during_p99 = during[98]
            # Floor the bound at 2ms: sub-100us baselines flap on
            # scheduler noise, which is not partition interference.
            assert during_p99 <= max(2 * base_p99, 2_000_000), (
                f"sibling write p99 {during_p99}ns vs baseline "
                f"{base_p99}ns"
            )
            # Per-partition readiness on /healthz.
            pay0 = cluster.nodes[(0, 0)]._health_payload()
            pay1 = cluster.nodes[(1, 0)]._health_payload()
            assert pay0["partition"] == 0
            assert pay0["partition_state"] != "live"
            assert pay0["status"] == "degraded"
            assert pay1["partition"] == 1
            assert pay1["partition_state"] == "live"
            # METRICS integer lines carry the same verdict.
            m0 = dict(
                ln.split(":", 1)
                for ln in cluster.nodes[(0, 0)]._metrics_wire().splitlines()
                if ":" in ln and not ln.startswith("METRICS")
            )
            assert int(m0["partition.state"]) > 0
            assert m0["partition.id"] == "0"
            # Heal: free the engine, poll -> live, healed event.
            cluster.engines[(0, 0)].truncate()
            assert wait_for(
                lambda: cluster.nodes[(0, 0)]._overload.poll_once() == 0
            )
            events = rec.last(0)
            degraded = [
                e for e in events if e.kind == "partition_degraded"
            ]
            healed = [e for e in events if e.kind == "partition_healed"]
            assert degraded and all(
                e.fields["partition"] == 0 for e in degraded
            )
            assert healed and all(
                e.fields["partition"] == 0 for e in healed
            )
        finally:
            c0.close()
            c1.close()
    finally:
        cluster.close()


# ----------------------------------------------------- the chaos headline


def test_chaos_kill_one_replica_per_partition_mid_storm():
    """4 partitions x 2 replicas; a write storm runs against the primary
    replicas while replica B of EVERY partition dies abruptly; the storm
    never stalls, the surviving replicas never leave live (flight +
    METRICS), and after the B replicas rejoin, every partition
    reconverges to a bit-identical per-partition root with zero
    cross-partition interference."""
    P, R = 4, 2
    rec = get_recorder()
    rec.clear()
    cluster = PartCluster(P, R)
    storm_errors: list[BaseException] = []
    try:
        pc = PartitionedClient(
            [cluster.addr[0][0]], timeout=5.0
        ).connect()
        # Phase 1: seed every partition and wait for replica convergence,
        # so the killed replicas hold real pre-kill state.
        seed_keys = {
            p: keys_for(p, P, 40, "seed") for p in range(P)
        }
        for p in range(P):
            for i, k in enumerate(seed_keys[p]):
                pc.set(k, f"s{i}")
        for p in range(P):
            assert wait_for(
                lambda p=p: cluster.root(p, 0) == cluster.root(p, 1)
                and cluster.root(p, 0) != b"",
                timeout=15,
            ), f"partition {p} replicas never converged pre-kill"

        # Phase 2: the storm, with one replica per partition dying at
        # fixed points mid-stream (deterministic schedule, fixed keys).
        storm_keys = {
            p: keys_for(p, P, 120, "storm") for p in range(P)
        }
        stop_storm = threading.Event()

        def storm() -> None:
            try:
                i = 0
                while not stop_storm.is_set():
                    for p in range(P):
                        k = storm_keys[p][i % 120]
                        pc.set(k, f"w{i}")
                    i += 1
            except BaseException as e:
                storm_errors.append(e)

        t = threading.Thread(target=storm, daemon=True)
        t.start()
        time.sleep(0.3)
        for p in range(P):  # the kill wave: one replica in EVERY partition
            cluster.kill(p, 1)
            time.sleep(0.1)
        # Storm keeps running against the survivors; sample their state.
        time.sleep(0.5)
        for p in range(P):
            metrics = dict(
                ln.split(":", 1)
                for ln in cluster.nodes[(p, 0)]._metrics_wire().splitlines()
                if ":" in ln and not ln.startswith("METRICS")
            )
            assert metrics["partition.state"] == "0", (
                f"surviving replica of partition {p} left live mid-storm"
            )
        time.sleep(0.4)
        stop_storm.set()
        t.join(timeout=10)
        assert not storm_errors, f"storm failed: {storm_errors[0]!r}"

        # Flight: NO partition ever degraded — replica death sheds
        # nothing on the survivors (partition-local fault containment).
        assert [
            e for e in rec.last(0) if e.kind == "partition_degraded"
        ] == []

        # Phase 3: the killed replicas rejoin (warm engines, stale by the
        # storm delta) and one anti-entropy cycle per partition repairs
        # them from their sibling — partition-local, no cross-talk.
        for p in range(P):
            cluster.start_node(p, 1)
        for p in range(P):
            host, _, port = cluster.addr[p][0].rpartition(":")
            cluster.nodes[(p, 1)].sync_manager.sync_once(host, int(port))
        for p in range(P):
            assert wait_for(
                lambda p=p: cluster.root(p, 0) == cluster.root(p, 1),
                timeout=15,
            ), f"partition {p} did not reconverge after rejoin"
            assert cluster.root(p, 0) != b""
        # Bit-identical per-partition roots, all distinct across
        # partitions (disjoint keyspaces).
        roots = {p: cluster.root(p, 0) for p in range(P)}
        assert len(set(roots.values())) == P

        # Zero cross-partition interference: every engine is partition-
        # pure — no storm key leaked into a foreign replica group.
        for p in range(P):
            for q in range(P):
                for k in storm_keys[q][:10]:
                    present = (
                        cluster.engines[(p, 0)].get(k.encode())
                        is not None
                    )
                    assert present == (p == q), (
                        f"key of partition {q} on partition {p}"
                    )
        # And the storm's data is all there, readable through the map.
        for p in range(P):
            for k in storm_keys[p][:20]:
                assert pc.get(k) is not None
        pc.close()
    finally:
        cluster.close()


# ------------------------------------------------------- obs / top / gate


def test_top_part_column_and_sample():
    from merklekv_tpu.obs import top as top_mod

    cluster = PartCluster(2, 1)
    try:
        s = top_mod.sample_node(cluster.addr[1][0])
        assert s.ok, s.error
        assert s.partition == 1
        table = top_mod.render_table({}, {cluster.addr[1][0]: s})
        assert "PART" in table.splitlines()[0]
        row = table.splitlines()[2]
        assert row.split()[1] == "1"
    finally:
        cluster.close()


def test_blackbox_partition_scope_classification():
    from merklekv_tpu.obs.blackbox import (
        Report,
        SpillDoc,
        TimelineEntry,
        find_anomalies,
        partition_incident_scope,
    )
    from merklekv_tpu.obs.flightrec import FlightEvent

    def doc(node, pid, events):
        evs = [
            FlightEvent(
                seq=i + 1,
                wall_ns=1_000 + i,
                mono_ns=i,
                kind=k,
                fields=dict(f),
            )
            for i, (k, f) in enumerate(events)
        ]
        return SpillDoc(
            path=f"/x/{node}/flight.bin",
            meta={"node": node},
            events=evs,
            samples=[],
        )

    base = [("node_start", {"port": 1, "partition": None})]

    def mk(nodes):
        docs = []
        for node, pid, extra in nodes:
            events = [("node_start", {"port": 1, "partition": pid})]
            events += extra
            docs.append(doc(node, pid, events))
        r = Report(docs=docs)
        for d in docs:
            for ev in d.events:
                r.timeline.append(TimelineEntry(node=d.node, event=ev))
        r.anomalies = find_anomalies(docs, r.timeline)
        return r

    degraded = (
        "partition_degraded",
        {"partition": 0, "level": "read_only", "reason": "disk"},
    )
    # One partition sick -> partition-local verdict.
    r = mk([
        ("a", 0, [degraded]),
        ("b", 1, []),
        ("c", 2, []),
    ])
    scope = partition_incident_scope(r)
    assert "PARTITION-LOCAL" in scope and "partition 0" in scope
    # Every partition sick -> cluster-wide verdict.
    r = mk([
        ("a", 0, [degraded]),
        ("b", 1, [(
            "partition_degraded",
            {"partition": 1, "level": "shedding", "reason": "memory"},
        )]),
    ])
    assert "CLUSTER-WIDE" in partition_incident_scope(r)
    # Unpartitioned spills -> no verdict at all.
    r = Report(docs=[doc("a", None, base)])
    assert partition_incident_scope(r) is None


def test_bench_gate_scale_out_direction():
    import tools.bench_gate as bench_gate

    assert not bench_gate.lower_is_better(
        "scale_out_throughput",
        "events/s (4 partitions x 1 io worker, pipelined SET)",
    )

"""Native storage engines via ctypes: semantics, persistence, Merkle parity.

Mirrors the reference's engine unit tests (rwlock_engine.rs:439-594,
sled_engine.rs) plus cross-checks the native HASH/Merkle path against the
Python CPU golden core.
"""

import tempfile
import threading

import pytest

from merklekv_tpu.merkle import MerkleTree
from merklekv_tpu.native_bindings import NativeEngine, NativeError


@pytest.fixture
def eng():
    with NativeEngine("mem") as e:
        yield e


def test_basic_ops(eng):
    assert eng.get(b"missing") is None
    eng.set(b"a", b"1")
    assert eng.get(b"a") == b"1"
    assert eng.exists(b"a")
    assert not eng.exists(b"b")
    assert eng.dbsize() == 1
    assert eng.delete(b"a")
    assert not eng.delete(b"a")
    assert eng.dbsize() == 0


def test_values_with_spaces_tabs_unicode(eng):
    eng.set(b"k", b"value with spaces\tand tabs")
    assert eng.get(b"k") == b"value with spaces\tand tabs"
    eng.set("clé".encode(), "välue☃".encode())
    assert eng.get("clé".encode()) == "välue☃".encode()


def test_numeric_semantics(eng):
    # Missing key: created as the amount (reference rwlock_engine.rs:252-320).
    assert eng.increment(b"n", 5) == 5
    assert eng.increment(b"n", 1) == 6
    assert eng.decrement(b"n", 10) == -4
    assert eng.decrement(b"m", 3) == -3
    eng.set(b"s", b"abc")
    with pytest.raises(NativeError, match="not a valid number"):
        eng.increment(b"s", 1)


def test_append_prepend(eng):
    assert eng.append(b"k", b"world") == b"world"  # create-if-missing
    assert eng.prepend(b"k", b"hello ") == b"hello world"
    assert eng.append(b"k", b"!") == b"hello world!"


def test_scan_sorted_and_prefixed(eng):
    for k in [b"b:2", b"a:1", b"b:1", b"c"]:
        eng.set(k, b"x")
    assert eng.scan() == [b"a:1", b"b:1", b"b:2", b"c"]
    assert eng.scan(b"b:") == [b"b:1", b"b:2"]
    assert eng.scan(b"zz") == []


def test_truncate_and_memory(eng):
    eng.set(b"k1", b"v1")
    eng.set(b"k2", b"v2")
    assert eng.memory_usage() == 8
    eng.truncate()
    assert eng.dbsize() == 0


def test_snapshot_sorted(eng):
    eng.set(b"z", b"3")
    eng.set(b"a", b"1")
    eng.set(b"m", b"2")
    assert eng.snapshot() == [(b"a", b"1"), (b"m", b"2"), (b"z", b"3")]


def test_merkle_root_matches_cpu_golden(eng):
    items = [(f"key{i:03d}", f"val{i * 7}") for i in range(57)]
    for k, v in items:
        eng.set(k.encode(), v.encode())
    expect = MerkleTree.from_items(items).root_hash()
    assert eng.merkle_root() == expect


def test_merkle_root_empty(eng):
    assert eng.merkle_root() is None


def test_concurrent_mixed_load(eng):
    # Reference-style thread stress (rwlock_engine.rs:487-593).
    def writer(tid):
        for i in range(200):
            eng.set(f"t{tid}:{i}".encode(), str(i).encode())

    def reader():
        for _ in range(200):
            eng.get(b"t0:0")
            eng.dbsize()

    def bumper():
        for _ in range(200):
            eng.increment(b"shared", 1)

    threads = (
        [threading.Thread(target=writer, args=(t,)) for t in range(4)]
        + [threading.Thread(target=reader) for _ in range(2)]
        + [threading.Thread(target=bumper) for _ in range(2)]
    )
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert eng.dbsize() == 4 * 200 + 1
    assert eng.get(b"shared") == b"400"


def test_log_engine_persistence():
    with tempfile.TemporaryDirectory() as d:
        with NativeEngine("log", d) as e:
            e.set(b"persist", b"yes")
            e.set(b"gone", b"x")
            e.delete(b"gone")
            e.increment(b"count", 7)
            e.sync()
        with NativeEngine("log", d) as e2:
            assert e2.get(b"persist") == b"yes"
            assert e2.get(b"gone") is None
            assert e2.get(b"count") == b"7"
            assert e2.dbsize() == 2


def test_log_engine_torn_tail_then_write_survives_second_restart():
    # Regression: replay used to stop at a torn tail record without
    # truncating the file; the log was then reopened O_APPEND, so writes
    # made after recovery landed *behind* the corrupt bytes and the next
    # replay silently dropped them.
    import os

    with tempfile.TemporaryDirectory() as d:
        with NativeEngine("log", d) as e:
            e.set(b"keep", b"1")
            e.sync()
        log = os.path.join(d, "data.log")
        with open(log, "ab") as f:
            f.write(b"\x01\xff\xff")  # torn record: op + partial klen
        with NativeEngine("log", d) as e2:
            assert e2.get(b"keep") == b"1"
            e2.set(b"after-recovery", b"2")
            e2.sync()
        with NativeEngine("log", d) as e3:
            assert e3.get(b"keep") == b"1"
            assert e3.get(b"after-recovery") == b"2"


def test_log_engine_corrupt_length_tail_truncated():
    # A tail whose lengths are absurd (claimed > 64 MiB) must also be cut.
    import os

    with tempfile.TemporaryDirectory() as d:
        with NativeEngine("log", d) as e:
            e.set(b"a", b"1")
            e.sync()
        log = os.path.join(d, "data.log")
        with open(log, "ab") as f:
            f.write(b"\x01" + (0xFFFFFFFF).to_bytes(4, "little") * 2 + b"junk")
        with NativeEngine("log", d) as e2:
            e2.set(b"b", b"2")
            e2.sync()
        with NativeEngine("log", d) as e3:
            assert e3.get(b"a") == b"1"
            assert e3.get(b"b") == b"2"


def test_log_engine_truncate_persists():
    with tempfile.TemporaryDirectory() as d:
        with NativeEngine("log", d) as e:
            e.set(b"a", b"1")
            e.truncate()
            e.set(b"b", b"2")
        with NativeEngine("log", d) as e2:
            assert e2.get(b"a") is None
            assert e2.get(b"b") == b"2"


# ------------------------------------------------------- tombstones & LWW


def test_get_with_ts_atomic_pair(eng):
    eng.set_with_ts(b"k", b"v", 123)
    assert eng.get_with_ts(b"k") == (b"v", 123)
    assert eng.get_with_ts(b"missing") is None


def test_delete_records_tombstone(eng):
    eng.set(b"k", b"v")
    assert eng.delete(b"k")
    ts = eng.tombstone_ts(b"k")
    assert ts is not None and ts > 0
    assert eng.tombstones() == [(b"k", ts)]


def test_delete_quiet_records_no_tombstone(eng):
    """Mirror deletes (pairwise anti-entropy) must not fabricate deletion
    intent — a tombstone-at-now would kill disjoint writes cluster-wide."""
    eng.set(b"k", b"v")
    assert eng.delete_quiet(b"k")
    assert eng.tombstone_ts(b"k") is None


def test_set_clears_tombstone(eng):
    eng.set(b"k", b"v")
    eng.delete(b"k")
    eng.set(b"k", b"v2")
    assert eng.tombstone_ts(b"k") is None
    assert eng.get(b"k") == b"v2"


def test_set_if_newer_respects_entry_and_tombstone(eng):
    eng.set_with_ts(b"k", b"v", 100)
    assert not eng.set_if_newer(b"k", b"older", 99)
    assert eng.get(b"k") == b"v"
    assert eng.set_if_newer(b"k", b"tie", 100)  # tie installs (caller broke it)
    assert eng.set_if_newer(b"k", b"newer", 101)
    eng.delete_with_ts(b"k", 200)
    assert not eng.set_if_newer(b"k", b"stale", 199)  # older than tombstone
    assert eng.get(b"k") is None
    assert eng.set_if_newer(b"k", b"fresh", 200)  # value wins the ts tie
    assert eng.get(b"k") == b"fresh"
    assert eng.tombstone_ts(b"k") is None


def test_del_if_newer_value_wins_ties(eng):
    eng.set_with_ts(b"k", b"v", 100)
    assert not eng.delete_if_newer(b"k", 100)  # tie: value survives
    assert eng.get(b"k") == b"v"
    assert eng.delete_if_newer(b"k", 101)
    assert eng.get(b"k") is None
    assert eng.tombstone_ts(b"k") == 101
    # Advancing an absent key's tombstone still applies (blocks older sets).
    assert eng.delete_if_newer(b"other", 50)
    assert not eng.set_if_newer(b"other", b"old", 49)


def test_tombstones_prefix_filter(eng):
    eng.set(b"a1", b"x")
    eng.set(b"b1", b"x")
    eng.delete(b"a1")
    eng.delete(b"b1")
    tombs = eng.tombstones(b"a")
    assert [k for k, _ in tombs] == [b"a1"]


def test_log_engine_tombstone_survives_restart():
    with tempfile.TemporaryDirectory() as d:
        with NativeEngine("log", d) as e:
            e.set(b"k", b"v")
            e.delete(b"k")
            ts = e.tombstone_ts(b"k")
            e.sync()
        with NativeEngine("log", d) as e2:
            assert e2.get(b"k") is None
            assert e2.tombstone_ts(b"k") == ts
            # The persisted tombstone still arbitrates LWW after restart.
            assert not e2.set_if_newer(b"k", b"stale", ts - 1)
            assert e2.get(b"k") is None


def test_log_engine_tombstone_survives_compaction():
    with tempfile.TemporaryDirectory() as d:
        with NativeEngine("log", d) as e:
            e.set(b"live", b"v")
            e.set(b"dead", b"v")
            e.delete(b"dead")
            ts = e.tombstone_ts(b"dead")
            assert e.compact()
            e.sync()
        with NativeEngine("log", d) as e2:
            assert e2.get(b"live") == b"v"
            assert e2.tombstone_ts(b"dead") == ts


def test_incr_append_clear_tombstone(eng):
    """INCR/DECR/APPEND/PREPEND create live entries — they must supersede a
    deletion record like SET does, or the key is advertised live AND
    tombstoned at once (and compaction replay would kill the value)."""
    eng.set(b"n", b"5")
    eng.delete(b"n")
    assert eng.increment(b"n", 2) == 2  # missing counts as 0
    assert eng.tombstone_ts(b"n") is None
    eng.delete(b"n")
    assert eng.append(b"n", b"x") == b"x"
    assert eng.tombstone_ts(b"n") is None


def test_log_engine_incr_after_delete_survives_compact_restart():
    with tempfile.TemporaryDirectory() as d:
        with NativeEngine("log", d) as e:
            e.set(b"n", b"1")
            e.delete(b"n")
            e.increment(b"n", 7)
            assert e.compact()
            e.sync()
        with NativeEngine("log", d) as e2:
            assert e2.get(b"n") == b"7"
            assert e2.tombstone_ts(b"n") is None


def test_equal_ts_conflict_converges_by_digest(eng):
    """Exact-ts cross-writer conflict: set_if_newer breaks the tie by leaf
    digest (larger wins), so replicas applying in either order agree."""
    from merklekv_tpu.merkle.encoding import leaf_hash

    a, b = b"va", b"vb"
    winner = a if leaf_hash(b"ck", a) > leaf_hash(b"ck", b) else b
    # Order 1: a then b.
    eng.set_if_newer(b"ck", a, 100)
    eng.set_if_newer(b"ck", b, 100)
    assert eng.get(b"ck") == winner
    # Order 2 on a fresh engine: b then a — same winner.
    with NativeEngine("mem") as e2:
        e2.set_if_newer(b"ck", b, 100)
        e2.set_if_newer(b"ck", a, 100)
        assert e2.get(b"ck") == winner
    # Idempotent redelivery of the same value at the same ts still applies.
    assert eng.set_if_newer(b"ck", winner, 100)


def test_del_if_newer_noop_when_tombstone_newer(eng):
    eng.delete_with_ts(b"dk", 200)
    # An older deletion arriving late must report NOT applied (state did
    # not advance) so callers don't log/notify a no-op.
    assert not eng.delete_if_newer(b"dk", 100)
    assert eng.delete_if_newer(b"dk", 300)
    assert eng.tombstone_ts(b"dk") == 300


def test_log_engine_noop_deletes_do_not_grow_log():
    import os

    with tempfile.TemporaryDirectory() as d:
        with NativeEngine("log", d) as e:
            e.delete_with_ts(b"absent", 100)
            e.sync()
            size1 = os.path.getsize(os.path.join(d, "data.log"))
            # Re-deleting with the same/older ts advances nothing: the log
            # must not grow (DEL-miss-heavy traffic between compactions).
            for _ in range(50):
                e.delete_with_ts(b"absent", 100)
                e.delete_with_ts(b"absent", 50)
                e.delete_if_newer(b"absent", 90)
            e.sync()
            assert os.path.getsize(os.path.join(d, "data.log")) == size1


def test_log_engine_version_header_and_downgrade_refusal():
    import os
    import struct

    with tempfile.TemporaryDirectory() as d:
        log = os.path.join(d, "data.log")
        with NativeEngine("log", d) as e:
            e.set(b"k", b"v")
            e.sync()
            assert not e.log_version_refused()
        with open(log, "rb") as f:
            head = f.read(8)
        assert head[:4] == b"MKVL"
        assert struct.unpack("<I", head[4:])[0] == 2
        # Forge a future format version: the engine must refuse to replay
        # AND leave the file byte-identical (the old failure mode was
        # parsing unknown records as corruption and truncating the log).
        with open(log, "r+b") as f:
            f.seek(4)
            f.write(struct.pack("<I", 99))
        before = open(log, "rb").read()
        with NativeEngine("log", d) as e2:
            assert e2.log_version_refused()
            assert e2.get(b"k") is None  # refused: nothing replayed
            # Writes fail LOUDLY (the log can't record them) instead of
            # silently pretending to be durable.
            with pytest.raises(NativeError):
                e2.set(b"refused", b"x")
            # TRUNCATE (FLUSHDB) and compaction must not destroy the
            # refused file either — both would rewrite it as an empty v2
            # log, which is exactly the data loss the refusal prevents.
            e2.truncate()
            assert not e2.compact()
        assert open(log, "rb").read() == before


def test_log_engine_legacy_headerless_log_upgrades_on_open():
    """A legacy headerless log replays and is UPGRADED in place to a
    headered v2 snapshot: headerless files can already hold kOpDelTs
    records that a pre-DelTs binary would misparse as corruption and
    truncate, so the header (refuse-don't-truncate) is the only real
    downgrade protection."""
    import os
    import struct

    with tempfile.TemporaryDirectory() as d:
        log = os.path.join(d, "data.log")
        # Hand-write legacy records: op=kOpSetTs(4) klen vlen ts key val,
        # plus a kOpDelTs(5) tombstone — both existed before the header.
        with open(log, "wb") as f:
            f.write(struct.pack("<BII", 4, 3, 2) + struct.pack("<Q", 7)
                    + b"old" + b"vv")
            f.write(struct.pack("<BII", 5, 4, 0) + struct.pack("<Q", 9)
                    + b"dead")
        with NativeEngine("log", d) as e:
            assert e.get(b"old") == b"vv"
            assert e.tombstone_ts(b"dead") == 9
            e.set(b"new", b"nn")
            e.sync()
        with open(log, "rb") as f:
            head = f.read(8)
        assert head[:4] == b"MKVL"  # upgraded on open
        assert struct.unpack("<I", head[4:])[0] == 2
        with NativeEngine("log", d) as e2:
            assert e2.get(b"old") == b"vv"
            assert e2.get(b"new") == b"nn"
            assert e2.tombstone_ts(b"dead") == 9  # tombstone survived upgrade


def test_log_engine_garbage_short_file_gets_header():
    """A 1-7 byte torn/garbage file must not condemn the log to staying
    headerless forever: it is truncated and rewritten as a headered file."""
    import os
    import struct

    with tempfile.TemporaryDirectory() as d:
        log = os.path.join(d, "data.log")
        with open(log, "wb") as f:
            f.write(b"\x01\xff\xff")  # 3-byte torn record
        with NativeEngine("log", d) as e:
            e.set(b"fresh", b"1")
            e.sync()
        with open(log, "rb") as f:
            assert f.read(4) == b"MKVL"
        with NativeEngine("log", d) as e2:
            assert e2.get(b"fresh") == b"1"


def test_tomb_evictions_counter(eng):
    assert eng.tomb_evictions() == 0
    eng.delete_with_ts(b"t1", 10)
    assert eng.tomb_evictions() == 0  # far below the per-shard cap


def test_key_timestamps_bulk_export(eng):
    eng.set_with_ts(b"ka", b"1", 100)
    eng.set_with_ts(b"kb", b"2", 200)
    eng.set_with_ts(b"kc", b"3", 300)
    eng.delete_with_ts(b"kb", 400)
    assert sorted(eng.key_timestamps()) == [(b"ka", 100), (b"kc", 300)]

"""Tracing spans + metrics registry."""

import json
import logging

import pytest

from merklekv_tpu.utils.tracing import Metrics, get_metrics, span


def test_span_emits_json_and_aggregates(caplog):
    m = get_metrics()
    m.reset()
    with caplog.at_level(logging.INFO, logger="merklekv"):
        with span("test.op", peer="p1") as rec:
            rec["items"] = 3
    records = [json.loads(r.message) for r in caplog.records]
    assert records and records[0]["span"] == "test.op"
    assert records[0]["peer"] == "p1"
    assert records[0]["items"] == 3
    assert records[0]["seconds"] >= 0
    snap = m.snapshot()
    assert snap["spans"]["test.op"]["count"] == 1


def test_span_records_errors(caplog):
    get_metrics().reset()
    with caplog.at_level(logging.INFO, logger="merklekv"):
        with pytest.raises(ValueError):
            with span("test.fail"):
                raise ValueError("boom")
    rec = json.loads(caplog.records[0].message)
    assert rec["error"] == "ValueError: boom"


def test_metrics_counters_thread_safe():
    import threading

    m = Metrics()

    def bump():
        for _ in range(1000):
            m.inc("x")

    ts = [threading.Thread(target=bump) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert m.snapshot()["counters"]["x"] == 8000


def test_sync_manager_emits_metrics():
    from merklekv_tpu.cluster.sync import SyncManager
    from merklekv_tpu.native_bindings import NativeEngine, NativeServer

    get_metrics().reset()
    with NativeEngine("mem") as remote_eng:
        remote_eng.set(b"mk", b"mv")
        with NativeServer(remote_eng, "127.0.0.1", 0) as srv:
            srv.start()
            with NativeEngine("mem") as local_eng:
                SyncManager(local_eng, device="cpu").sync_once(
                    "127.0.0.1", srv.port
                )
    snap = get_metrics().snapshot()
    assert snap["counters"]["anti_entropy.syncs"] == 1
    assert snap["counters"]["anti_entropy.keys_repaired"] == 1
    assert snap["spans"]["anti_entropy.sync_once"]["count"] == 1

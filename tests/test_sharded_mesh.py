"""8-way host-platform mesh coverage for the sharded serving tree.

Unlike the in-process suite (which inherits conftest's virtual mesh), this
module spawns a FRESH interpreter that provisions its own 8-device CPU mesh
via ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` — the exact
recipe the CI integration job and the multichip probe's subprocess
delegation use — and asserts the sharded build, the per-shard-routed
incremental scatter, and the TREELEVEL answers are bit-identical to the
pure-python CPU golden tree across shard counts {1, 2, 8}, including an
update batch that straddles every shard boundary. One subprocess covers the
whole sweep (the jax import dominates, so per-count processes would triple
the cost for no isolation gain).
"""

import json
import os
import subprocess
import sys

import pytest

# integration: spawns a real interpreter. Keeps the subprocess out of the
# unit CI job; the integration job (and tier-1) run it on every PR.
pytestmark = pytest.mark.integration

_SWEEP = r"""
import json
import jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, @@REPO@@)

from merklekv_tpu.merkle.cpu import build_levels
from merklekv_tpu.merkle.encoding import leaf_hash
from merklekv_tpu.parallel.sharded_state import ShardedDeviceMerkleState

assert len(jax.devices()) == 8, jax.devices()


def golden_levels(items):
    return build_levels([leaf_hash(k, v) for k, v in sorted(items.items())])


def check_levels(st, items, what):
    glv = golden_levels(items)
    assert st.root_hex() == glv[-1][0].hex(), what
    for lvl in range(len(glv)):
        rows, n = st.level_nodes(lvl, 0, len(glv[lvl]))
        assert n == len(items), (what, lvl)
        assert [d for _, d in rows] == glv[lvl], (what, "level", lvl)


for shards in (1, 2, 8):
    items = {b"mk%05d" % i: b"v%d" % i for i in range(141)}
    st = ShardedDeviceMerkleState.from_items(items.items(), shards=shards)
    check_levels(st, items, (shards, "build"))

    # Scatter batch straddling EVERY shard boundary (last leaf of shard b,
    # first leaf of shard b+1) plus both keyspace extremes.
    skeys = sorted(items)
    l = st._capacity // shards
    batch = {skeys[0]: b"first", skeys[-1]: b"last"}
    for b in range(1, shards):
        for p in (b * l - 1, b * l):
            if p < len(skeys):
                batch[skeys[p]] = b"x%d" % p
    items.update(batch)
    st.apply(list(batch.items()))
    st.flush_pending()
    assert st.incremental_batches >= 1, shards
    check_levels(st, items, (shards, "scatter"))

    # Structural batch (inserts shift leaves ACROSS shard boundaries).
    changes = []
    for i in range(400, 470):
        items[b"aa%05d" % i] = b"n%d" % i
        changes.append((b"aa%05d" % i, b"n%d" % i))
    del items[b"mk00007"]
    changes.append((b"mk00007", None))
    st.apply(changes)
    check_levels(st, items, (shards, "restructure"))

    if shards > 1:
        assert not st._levels[0].sharding.is_fully_replicated, shards

print(json.dumps({"ok": True, "shard_counts": [1, 2, 8]}))
"""


def test_eight_way_host_mesh_parity(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "mesh_sweep.py"
    script.write_text(_SWEEP.replace("@@REPO@@", repr(repo)))
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    out = subprocess.run(
        [sys.executable, str(script)],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
    )
    assert out.returncode == 0, f"sweep failed:\n{out.stdout}\n{out.stderr}"
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["ok"] is True and rec["shard_counts"] == [1, 2, 8]

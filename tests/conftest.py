"""Test harness configuration.

JAX tests run on a virtual 8-device CPU mesh (multi-chip TPU hardware is not
available in CI); the env vars must be set before jax is first imported, so
this conftest sets them at collection time. Bench runs (bench.py) are separate
and use the real TPU chip.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test")
    config.addinivalue_line("markers", "benchmark: performance test")
    config.addinivalue_line("markers", "integration: spawns real server processes")

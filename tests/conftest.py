"""Test harness configuration.

JAX tests run on a virtual 8-device CPU mesh (multi-chip TPU hardware is not
available in CI). The environment pins jax to the tunneled TPU backend
("axon") via a sitecustomize hook that sets the ``jax_platforms`` config
value directly — an env-var override is ignored — so the CPU selection must
also go through ``jax.config.update`` before any backend is initialized.
Bench runs (bench.py) are separate and use the real TPU chip.
"""

import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# MERKLEKV_TEST_BACKEND=tpu runs the suite against the real chip instead of
# the virtual CPU mesh — this enables the compiled-Pallas kernel tests
# (gated on backend == "tpu") that are skipped on the CPU mesh.
if os.environ.get("MERKLEKV_TEST_BACKEND", "cpu") != "tpu":
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test")
    config.addinivalue_line("markers", "benchmark: performance test")
    config.addinivalue_line("markers", "integration: spawns real server processes")

"""Snapshot serialization + Merkle root stamping/verification."""

import os

import pytest

from merklekv_tpu.merkle.encoding import EMPTY_ROOT_HEX, leaf_hash
from merklekv_tpu.native_bindings import NativeEngine
from merklekv_tpu.storage import snapshot as snapmod
from merklekv_tpu.testing.faults import corrupt_file, truncate_file


def _items(n):
    return [
        (b"key%04d" % i, b"value-%d" % i, 10_000 + i) for i in range(n)
    ]


def _write(tmp_path, items=None, tombs=None, wal_seq=7, root=None, seq=1):
    items = _items(30) if items is None else items
    tombs = [(b"gone", 999), (b"also-gone", 1234)] if tombs is None else tombs
    if root is None:
        root = snapmod.compute_root_hex(
            [(k, v) for k, v, _ in items], engine="cpu"
        )
    return snapmod.write_snapshot(
        str(tmp_path), seq, items, tombs, wal_seq, root
    )


def test_roundtrip(tmp_path):
    items = _items(30)
    path = _write(tmp_path, items=items)
    snap = snapmod.read_snapshot(path)
    assert snap.items == items
    assert snap.tombstones == [(b"gone", 999), (b"also-gone", 1234)]
    assert snap.wal_seq == 7
    assert snapmod.verify_snapshot(snap, engine="cpu") == snap.root_hex


def test_root_matches_native_engine(tmp_path):
    """The stamp equals what the serving engine answers for HASH — one
    Merkle spec across native, CPU, device, and the snapshot stamp."""
    eng = NativeEngine("mem")
    try:
        for k, v, ts in _items(50):
            eng.set_with_ts(k, v, ts)
        native_root = eng.merkle_root().hex()
        stamped = snapmod.compute_root_hex(
            [(k, v) for k, v, _ in _items(50)], engine="cpu"
        )
        assert stamped == native_root
    finally:
        eng.close()


def test_root_device_path_parity(tmp_path):
    """CPU fallback and the device bulk path stamp the same root (the
    virtual-CPU jax backend stands in for the chip in CI)."""
    pairs = [(k, v) for k, v, _ in _items(64)]
    assert snapmod.compute_root_hex(pairs, engine="cpu") == (
        snapmod.compute_root_hex(pairs, engine="tpu")
    )


def test_empty_root_stamp(tmp_path):
    path = _write(tmp_path, items=[], tombs=[], root=EMPTY_ROOT_HEX)
    snap = snapmod.read_snapshot(path)
    assert snap.root_hex == EMPTY_ROOT_HEX
    assert snapmod.verify_snapshot(snap, engine="cpu") == EMPTY_ROOT_HEX


def test_crc_catches_bit_rot(tmp_path):
    path = _write(tmp_path)
    corrupt_file(path, os.path.getsize(path) // 2)
    with pytest.raises(snapmod.SnapshotCorruptError):
        snapmod.read_snapshot(path)


def test_short_file_is_corrupt(tmp_path):
    path = _write(tmp_path)
    truncate_file(path, os.path.getsize(path) - 9)
    with pytest.raises(snapmod.SnapshotCorruptError):
        snapmod.read_snapshot(path)


def test_wrong_stamp_is_root_mismatch(tmp_path):
    """A decodable snapshot whose content hashes differently from its
    header stamp raises the DISTINCT error recovery keys off of."""
    bogus = leaf_hash(b"not", b"the-state").hex()
    path = _write(tmp_path, root=bogus)
    snap = snapmod.read_snapshot(path)  # CRC is fine — content is intact
    with pytest.raises(snapmod.RootMismatchError) as ei:
        snapmod.verify_snapshot(snap, engine="cpu")
    assert ei.value.stamped == bogus
    assert ei.value.actual == snapmod.compute_root_hex(
        [(k, v) for k, v, _ in _items(30)], engine="cpu"
    )


def test_listing_orders_by_seq(tmp_path):
    for seq in (3, 1, 2):
        _write(tmp_path, seq=seq)
    assert [s for s, _ in snapmod.list_snapshots(str(tmp_path))] == [1, 2, 3]

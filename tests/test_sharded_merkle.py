"""SPMD Merkle build + diff over the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax

from merklekv_tpu.merkle.cpu import MerkleTree
from merklekv_tpu.merkle.diff import (
    align_replicas,
    diff_keys_multi,
    diff_keys_pair,
    divergence_masks,
)
from merklekv_tpu.merkle.encoding import leaf_hash
from merklekv_tpu.merkle.jax_engine import leaf_digests
from merklekv_tpu.ops.sha256 import digest_to_bytes
from merklekv_tpu.merkle.packing import pack_leaves
from merklekv_tpu.parallel import (
    make_mesh,
    sharded_anti_entropy_step,
    sharded_divergence,
    sharded_tree_root,
)


def _leafmap(items):
    return {k.encode(): leaf_hash(k, v) for k, v in items}


def _items(n, seed=0):
    rng = np.random.default_rng(seed)
    return [(f"k{rng.integers(0, 10**9):09d}:{i}", f"v{i}") for i in range(n)]


@pytest.mark.parametrize("n_dev,per_shard", [(8, 4), (8, 16), (4, 8), (2, 32)])
def test_sharded_root_matches_cpu(n_dev, per_shard):
    n = n_dev * per_shard
    items = _items(n, seed=n)
    cpu_root = MerkleTree.from_items(items).root_hash()

    ordered = sorted((k.encode(), v.encode()) for k, v in items)
    leaves = leaf_digests([k for k, _ in ordered], [v for _, v in ordered])
    mesh = make_mesh({"key": n_dev}, devices=jax.devices()[:n_dev])
    got = digest_to_bytes(np.asarray(sharded_tree_root(mesh, leaves)))
    assert got == cpu_root


def test_sharded_root_rejects_bad_shapes():
    mesh = make_mesh({"key": 4}, devices=jax.devices()[:4])
    with pytest.raises(ValueError):
        sharded_tree_root(mesh, np.zeros((10, 8), np.uint32))  # not divisible
    with pytest.raises(ValueError):
        sharded_tree_root(mesh, np.zeros((12, 8), np.uint32))  # L=3 not pow2


def test_divergence_masks_basic():
    a = _leafmap([("x", "1"), ("y", "2"), ("z", "3")])
    b = _leafmap([("x", "1"), ("y", "CHANGED"), ("w", "4")])
    aligned = align_replicas([a, b])
    diffs = diff_keys_multi(aligned)
    assert diffs[1] == [b"w", b"y", b"z"]
    assert diff_keys_pair(a, b) == [b"w", b"y", b"z"]
    # parity with the CPU tree's flat diff
    ta = MerkleTree.from_items([("x", "1"), ("y", "2"), ("z", "3")])
    tb = MerkleTree.from_items([("x", "1"), ("y", "CHANGED"), ("w", "4")])
    assert [k.decode() for k in diff_keys_pair(a, b)] == ta.diff_keys(tb)


def test_divergence_eight_replicas():
    base = _items(24, seed=3)
    replicas = []
    for r in range(8):
        items = dict(base)
        if r:
            items[f"extra{r}"] = "x"          # replica-only key
            items[base[r][0]] = "mutated"     # changed value
        replicas.append(_leafmap(items.items()))
    aligned = align_replicas(replicas)
    diffs = diff_keys_multi(aligned)
    for r in range(1, 8):
        assert set(diffs[r]) == {f"extra{r}".encode(), base[r][0].encode()}


def test_fused_anti_entropy_step_matches_cpu():
    """The fused hash+build+diff program agrees with the CPU core end to end."""
    n = 8 * 8
    items = sorted((f"fk{i:04d}", f"fv{i * 3}") for i in range(n))
    cpu_root = MerkleTree.from_items(items).root_hash()

    keys = [k.encode() for k, _ in items]
    values = [v.encode() for _, v in items]
    packed = pack_leaves(keys, values)

    local = _leafmap(items)
    mutated = dict(items)
    mutated[items[11][0]] = "CHANGED"
    replicas = [local, _leafmap(mutated.items()), dict(local)]
    aligned = align_replicas(replicas)

    mesh = make_mesh({"key": 8})
    root, masks, counts = sharded_anti_entropy_step(
        mesh, packed.blocks, packed.nblocks, aligned.digests, aligned.present
    )
    assert digest_to_bytes(np.asarray(root)) == cpu_root
    np.testing.assert_array_equal(
        np.asarray(counts), np.asarray([0, 1, 0], np.int32)
    )
    local_masks = np.asarray(divergence_masks(aligned.digests, aligned.present))
    np.testing.assert_array_equal(np.asarray(masks), local_masks)


def test_fused_step_rejects_bad_shapes():
    mesh = make_mesh({"key": 4}, devices=jax.devices()[:4])
    blocks = np.zeros((16, 1, 16), np.uint32)
    nblocks = np.ones((16,), np.int32)
    with pytest.raises(ValueError):  # digest axis mismatch
        sharded_anti_entropy_step(
            mesh, blocks, nblocks, np.zeros((2, 8, 8), np.uint32), np.zeros((2, 8), bool)
        )
    with pytest.raises(ValueError):  # empty keyspace
        sharded_anti_entropy_step(
            mesh,
            np.zeros((0, 1, 16), np.uint32),
            np.zeros((0,), np.int32),
            np.zeros((2, 0, 8), np.uint32),
            np.zeros((2, 0), bool),
        )


def test_sharded_divergence_matches_local():
    base = _items(32, seed=9)
    replicas = [_leafmap(base)]
    mutated = dict(base)
    mutated[base[5][0]] = "zzz"
    del mutated[base[7][0]]
    replicas.append(_leafmap(mutated.items()))
    aligned = align_replicas(replicas)

    mesh = make_mesh({"key": 8})
    masks, counts = sharded_divergence(mesh, aligned.digests, aligned.present)
    local = np.asarray(divergence_masks(aligned.digests, aligned.present))
    np.testing.assert_array_equal(np.asarray(masks), local)
    np.testing.assert_array_equal(
        np.asarray(counts), local.sum(axis=1).astype(np.int32)
    )


@pytest.mark.parametrize(
    "dr,dk,r,n",
    [
        (2, 4, 4, 16),
        (2, 2, 6, 8),
        (4, 2, 4, 64),
        # BASELINE config 5's replica scale: 64 replicas sharded 4-ways on
        # the replica axis (16 digest rows per device instead of 64).
        (4, 2, 64, 8),
    ],
)
def test_divergence_2d_matches_1d_and_host(dr, dk, r, n):
    """2-D (replica x key) sharded divergence is bit-identical to the
    host-side golden mask and to the key-only sharded program."""
    from merklekv_tpu.merkle.diff import divergence_masks_np
    from merklekv_tpu.parallel.sharded_merkle import sharded_divergence_2d

    rng = np.random.RandomState(17)
    base = rng.randint(0, 2**32, size=(1, n, 8), dtype=np.uint64).astype(np.uint32)
    digests = np.tile(base, (r, 1, 1))
    present = np.ones((r, n), bool)
    # Divergent digests + presence asymmetries in both directions.
    digests[1, 0, 0] ^= 1
    digests[r - 1, n - 1, 3] ^= 7
    present[1, 2] = False          # missing on replica 1
    present[0, 3] = False          # missing on the reference
    present[:, 4] = False          # missing everywhere (no divergence)

    mesh = make_mesh({"replica": dr, "key": dk},
                     devices=jax.devices()[: dr * dk])
    masks, counts = sharded_divergence_2d(mesh, digests, present)
    masks, counts = np.asarray(masks), np.asarray(counts)

    golden = divergence_masks_np(digests, present)
    np.testing.assert_array_equal(masks, golden)
    np.testing.assert_array_equal(counts, golden.sum(axis=1).astype(np.int32))
    assert not masks[0].any()  # reference row self-compares clean


def test_divergence_2d_rejects_bad_shapes():
    from merklekv_tpu.parallel.sharded_merkle import sharded_divergence_2d

    mesh = make_mesh({"replica": 2, "key": 4})
    digests = np.zeros((3, 16, 8), np.uint32)  # 3 % 2 != 0
    present = np.ones((3, 16), bool)
    with pytest.raises(ValueError, match="replica count"):
        sharded_divergence_2d(mesh, digests, present)
    digests = np.zeros((2, 15, 8), np.uint32)  # 15 % 4 != 0
    present = np.ones((2, 15), bool)
    with pytest.raises(ValueError, match="key count"):
        sharded_divergence_2d(mesh, digests, present)

"""Golden tests for the CPU Merkle core.

Mirrors the reference's inline Merkle suite (/root/reference/src/store/merkle.rs:207-1184):
determinism across insertion orders, manual root reconstruction, odd-leaf
promotion shape, NUL/unicode robustness, diff correctness under seeded random
mutation, and a delete/restore stress run.
"""

import hashlib
import random
import struct

import pytest

from merklekv_tpu.merkle import (
    EMPTY_ROOT_HEX,
    MerkleTree,
    build_levels,
    encode_leaf,
    leaf_hash,
    node_hash,
)


def manual_leaf(key: str, value: str) -> bytes:
    kb, vb = key.encode(), value.encode()
    buf = struct.pack(">I", len(kb)) + kb + struct.pack(">I", len(vb)) + vb
    return hashlib.sha256(buf).digest()


class TestEncoding:
    def test_leaf_encoding_is_length_prefixed(self):
        assert encode_leaf("a", "b") == b"\x00\x00\x00\x01a\x00\x00\x00\x01b"

    def test_leaf_encoding_injective_on_ambiguous_concat(self):
        # "a:" + ":b" vs "a" + "::b" would collide under naive concat
        assert encode_leaf("a:", ":b") != encode_leaf("a", "::b")
        assert leaf_hash("a:", ":b") != leaf_hash("a", "::b")

    def test_leaf_hash_matches_manual(self):
        assert leaf_hash("key1", "value1") == manual_leaf("key1", "value1")

    def test_nul_and_unicode(self):
        assert leaf_hash("k\x00ey", "v") != leaf_hash("key", "\x00v")
        assert leaf_hash("héllo", "wörld") == manual_leaf("héllo", "wörld")

    def test_empty_key_value(self):
        assert leaf_hash("", "") == hashlib.sha256(b"\x00" * 8).digest()


class TestBuild:
    def test_empty_tree(self):
        t = MerkleTree()
        assert t.root_hash() is None
        assert t.root_hex() == EMPTY_ROOT_HEX
        assert t.node_count() == 0
        assert t.preorder_hashes() == []

    def test_single_leaf_root_is_leaf_hash(self):
        t = MerkleTree()
        t.insert("k", "v")
        assert t.root_hash() == leaf_hash("k", "v")
        assert t.node_count() == 1

    def test_two_leaf_manual_reconstruction(self):
        t = MerkleTree.from_items([("a", "1"), ("b", "2")])
        expected = node_hash(leaf_hash("a", "1"), leaf_hash("b", "2"))
        assert t.root_hash() == expected
        assert t.node_count() == 3

    def test_four_leaf_manual_reconstruction(self):
        items = [("a", "1"), ("b", "2"), ("c", "3"), ("d", "4")]
        t = MerkleTree.from_items(items)
        l = [leaf_hash(k, v) for k, v in items]
        expected = node_hash(node_hash(l[0], l[1]), node_hash(l[2], l[3]))
        assert t.root_hash() == expected
        assert t.node_count() == 7

    def test_three_leaf_odd_promotion(self):
        items = [("a", "1"), ("b", "2"), ("c", "3")]
        t = MerkleTree.from_items(items)
        l = [leaf_hash(k, v) for k, v in items]
        # c is promoted unchanged to level 1; root = H(H(a||b) || c)
        expected = node_hash(node_hash(l[0], l[1]), l[2])
        assert t.root_hash() == expected
        assert t.node_count() == 5  # 3 leaves + H(ab) + root

    def test_five_leaf_promotion_chain(self):
        items = [(c, c) for c in "abcde"]
        t = MerkleTree.from_items(items)
        l = [leaf_hash(c, c) for c in "abcde"]
        lvl1 = [node_hash(l[0], l[1]), node_hash(l[2], l[3]), l[4]]
        lvl2 = [node_hash(lvl1[0], lvl1[1]), l[4]]
        expected = node_hash(lvl2[0], lvl2[1])
        assert t.root_hash() == expected

    def test_determinism_across_insertion_orders(self):
        items = [(f"key{i}", f"val{i}") for i in range(37)]
        roots = set()
        for seed in range(5):
            shuffled = items[:]
            random.Random(seed).shuffle(shuffled)
            roots.add(MerkleTree.from_items(shuffled).root_hash())
        assert len(roots) == 1

    def test_sorted_by_byte_order(self):
        # 'Z' < 'a' in byte order; ensure ordering is bytes not locale
        t1 = MerkleTree.from_items([("Z", "1"), ("a", "2")])
        expected = node_hash(leaf_hash("Z", "1"), leaf_hash("a", "2"))
        assert t1.root_hash() == expected

    def test_value_update_changes_root(self):
        t = MerkleTree.from_items([("a", "1"), ("b", "2")])
        r1 = t.root_hash()
        t.insert("a", "CHANGED")
        assert t.root_hash() != r1

    def test_remove_then_restore_root_roundtrip(self):
        t = MerkleTree.from_items([(f"k{i}", f"v{i}") for i in range(20)])
        r = t.root_hash()
        t.remove("k7")
        assert t.root_hash() != r
        t.insert("k7", "v7")
        assert t.root_hash() == r

    def test_build_levels_shapes(self):
        hashes = [leaf_hash(str(i), str(i)) for i in range(6)]
        levels = build_levels(hashes)
        assert [len(l) for l in levels] == [6, 3, 2, 1]

    def test_preorder_root_first(self):
        t = MerkleTree.from_items([(c, c) for c in "abc"])
        pre = t.preorder_hashes()
        assert pre[0] == t.root_hash()
        assert len(pre) == t.node_count()
        # preorder: root, H(ab), a, b, c
        l = [leaf_hash(c, c) for c in "abc"]
        assert pre == [t.root_hash(), node_hash(l[0], l[1]), l[0], l[1], l[2]]

    def test_inorder_keys_and_leaves_sorted(self):
        t = MerkleTree.from_items([("b", "2"), ("a", "1"), ("c", "3")])
        assert t.inorder_keys() == ["a", "b", "c"]
        assert [k for k, _ in t.leaves()] == ["a", "b", "c"]
        assert t.leaves()[0][1] == leaf_hash("a", "1")


class TestDiff:
    def test_identical_trees_no_diff(self):
        a = MerkleTree.from_items([(f"k{i}", f"v{i}") for i in range(50)])
        b = MerkleTree.from_items([(f"k{i}", f"v{i}") for i in range(50)])
        assert a.diff_keys(b) == []
        assert a.root_hash() == b.root_hash()

    def test_value_divergence_detected(self):
        a = MerkleTree.from_items([("x", "1"), ("y", "2")])
        b = MerkleTree.from_items([("x", "1"), ("y", "DIFFERENT")])
        assert a.diff_keys(b) == ["y"]

    def test_missing_keys_both_directions(self):
        a = MerkleTree.from_items([("only_a", "1"), ("both", "2")])
        b = MerkleTree.from_items([("only_b", "3"), ("both", "2")])
        assert a.diff_keys(b) == ["only_a", "only_b"]
        assert b.diff_keys(a) == ["only_a", "only_b"]

    def test_diff_first_key(self):
        a = MerkleTree.from_items([("a", "1"), ("z", "9")])
        b = MerkleTree.from_items([("a", "X"), ("z", "Y")])
        assert a.diff_first_key(b) == "a"
        assert MerkleTree().diff_first_key(MerkleTree()) is None

    def test_seeded_random_divergence(self):
        rng = random.Random(1234)
        base = {f"key{i:04d}": f"val{i}" for i in range(300)}
        a = MerkleTree.from_items(base.items())

        mutated = dict(base)
        changed = set(rng.sample(sorted(base), 25))
        removed = set(rng.sample(sorted(base.keys() - changed), 10))
        added = {f"new{i}": "x" for i in range(7)}
        for k in changed:
            mutated[k] = mutated[k] + "_mut"
        for k in removed:
            del mutated[k]
        mutated.update(added)
        b = MerkleTree.from_items(mutated.items())

        expected = sorted(changed | removed | set(added))
        assert a.diff_keys(b) == expected

    def test_root_equality_iff_no_diff(self):
        rng = random.Random(7)
        for trial in range(20):
            n = rng.randrange(1, 40)
            items = {f"k{rng.randrange(100)}": str(rng.random()) for _ in range(n)}
            other = dict(items)
            if trial % 2:  # half the trials mutate the copy
                k = rng.choice(sorted(other))
                match rng.randrange(3):
                    case 0:
                        other[k] = other[k] + "_mut"
                    case 1:
                        del other[k]
                    case 2:
                        other[f"extra{trial}"] = "x"
            a = MerkleTree.from_items(items.items())
            b = MerkleTree.from_items(other.items())
            assert (a.root_hash() == b.root_hash()) == (a.diff_keys(b) == [])
            assert (items == other) == (a.diff_keys(b) == [])


@pytest.mark.slow
class TestStress:
    def test_200_key_delete_restore(self):
        items = [(f"key{i:03d}", f"value{i}") for i in range(200)]
        t = MerkleTree.from_items(items)
        original = t.root_hash()
        rng = random.Random(99)
        doomed = rng.sample([k for k, _ in items], 50)
        for k in doomed:
            t.remove(k)
        assert len(t) == 150
        for k in doomed:
            t.insert(k, f"value{int(k[3:])}")
        assert t.root_hash() == original

    def test_incremental_vs_batch_equivalence(self):
        # Lazy rebuild must equal one-shot build for any mutation sequence.
        rng = random.Random(5)
        t = MerkleTree()
        state: dict[str, str] = {}
        for step in range(500):
            k = f"k{rng.randrange(80)}"
            if rng.random() < 0.3 and state:
                t.remove(k)
                state.pop(k, None)
            else:
                v = f"v{step}"
                t.insert(k, v)
                state[k] = v
            if step % 97 == 0:
                fresh = MerkleTree.from_items(state.items())
                assert t.root_hash() == fresh.root_hash()
        fresh = MerkleTree.from_items(state.items())
        assert t.root_hash() == fresh.root_hash()
        assert t.node_count() == fresh.node_count()
        assert t.preorder_hashes() == fresh.preorder_hashes()

"""Device-plane fault containment (ISSUE 13): mirror-level chaos.

Per-rung ladder transitions on the virtual 8-device CPU mesh (conftest):
injected persistent failures at shard widths 8 / 2 / 1 walk the serving
backend down sharded(N) -> single-device -> CPU golden with BIT-IDENTICAL
roots and MONOTONE version stamps at every transition; a hang injection
proves the pump-alive invariant (queries never block on the dispatch
deadline); the integrity scrub catches injected silent corruption; the
re-warm probe climbs back to sharded(N) after heal; invalidate() leaves a
heartbeat in the flight timeline instead of going silent. A slow soak
cycles inject/heal repeatedly and checks for thread leaks.
"""

import threading
import time

import pytest

from merklekv_tpu.cluster.change_event import ChangeEvent, OpKind
from merklekv_tpu.cluster.mirror import DeviceTreeMirror
from merklekv_tpu.cluster.retry import RetryPolicy
from merklekv_tpu.device.guard import configure as configure_guard
from merklekv_tpu.device.ladder import DeviceBackendLadder
from merklekv_tpu.merkle.cpu import build_levels
from merklekv_tpu.merkle.encoding import leaf_hash
from merklekv_tpu.native_bindings import NativeEngine
from merklekv_tpu.testing.device_faults import DeviceFaultInjector

N_KEYS = 96
FAST_HEAL = RetryPolicy(first_delay=0.05, max_delay=0.2, jitter=0.0)


def _golden_root(eng) -> str:
    items = dict(eng.snapshot())
    return build_levels(
        [leaf_hash(k, v) for k, v in sorted(items.items())]
    )[-1][0].hex()


def _engine() -> NativeEngine:
    eng = NativeEngine()
    for i in range(N_KEYS):
        eng.set(b"lk:%04d" % i, b"v%d" % i)
    return eng


def _ev(key: bytes) -> ChangeEvent:
    return ChangeEvent(
        op=OpKind.SET, key=key.decode(), val=b"x", ts=1, src="t"
    )


def _mirror(eng, sharding="8", degrade_after=1, **kw) -> DeviceTreeMirror:
    top = 0 if sharding in ("off", "1") else int(sharding)
    ladder = DeviceBackendLadder(
        top, degrade_after=degrade_after, heal_policy=FAST_HEAL
    )
    kw.setdefault("scrub_interval_s", 0.0)
    kw.setdefault("max_staleness_ms", 50.0)
    return DeviceTreeMirror(eng, sharding=sharding, ladder=ladder, **kw)


def _wait(cond, timeout=120.0, poll=0.02) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(poll)
    return False


def _warm(m) -> None:
    m.start_warming()
    assert _wait(m.ready), "mirror never warmed"


@pytest.fixture(scope="module", autouse=True)
def _prewarm_programs():
    """Compile every program the ladder drills will dispatch (sharded-8
    build/scatter/root/levels, single-device ditto, and the tiny
    heal-probe shapes) so tests with tight deadlines measure dispatch,
    not first-jit compile — an undersized deadline reads a compile as a
    hang, which is exactly the production sizing rule DEPLOYMENT.md
    documents."""
    from merklekv_tpu.device.ladder import build_state_for_rung
    from merklekv_tpu.merkle.incremental import DeviceMerkleState
    from merklekv_tpu.parallel.sharded_state import ShardedDeviceMerkleState

    configure_guard(deadline_ms=120_000)
    items = [(b"lk:%04d" % i, b"v%d" % i) for i in range(N_KEYS)]
    for st in (
        ShardedDeviceMerkleState.from_items(items, shards=8),
        ShardedDeviceMerkleState.from_items(items, shards=2),
        DeviceMerkleState.from_items(items),
    ):
        st.apply([(b"lk:0000", b"prewarm")])
        st.root_hex()
        st.level_nodes(0, 0, 4)
    for rung in (8, 2, 1):
        build_state_for_rung(rung, [(b"mkv:heal-probe", b"ok")]).root_hex()
    yield
    configure_guard(deadline_ms=60_000)


@pytest.mark.parametrize(
    "sharding,match,expect_rung",
    [
        ("8", "shard8_*", 4),   # width-8 fault: largest healthy subset
        ("8", "shard*", 1),     # every sharded width sick: single-device
        ("2", "shard2_*", 1),
        ("8", "*", 0),          # whole device plane sick: CPU golden
    ],
)
def test_warm_build_lands_on_surviving_rung(sharding, match, expect_rung):
    """A persistently faulted rung never serves: the warm build walks the
    ladder and completes on the surviving backend, root bit-identical.
    The width-8-only fault proves degrade-and-RESHARD: the mesh narrows
    to the largest healthy power-of-two subset, not straight to one
    device."""
    eng = _engine()
    m = _mirror(eng, sharding=sharding)
    with DeviceFaultInjector(match=match, mode="fail"):
        _warm(m)
        assert m.backend_level() == expect_rung
        assert m.published_root_hex() == _golden_root(eng)
        rows, n = m.level_nodes(0, 0, 8)
        assert n == N_KEYS and len(rows) == 8
    m.close()


def test_drain_failure_degrades_stamps_monotone_then_reclimbs():
    """The acceptance drill: persistent sharded failure mid-serve ->
    rung-by-rung degrade to single-device with bit-identical roots and
    monotone stamps -> heal -> probe reclimbs to sharded(8) -> fresh
    writes serve bit-identically at full width."""
    from merklekv_tpu.obs.flightrec import get_recorder

    eng = _engine()
    m = _mirror(eng, sharding="8")
    _warm(m)
    assert m.backend_level() == 8
    assert m.published_root_hex() == _golden_root(eng)
    v0 = m.published_version()

    inj = DeviceFaultInjector(match="shard*", mode="fail").install()
    try:
        eng.set(b"lk:0000", b"CHANGED")
        m.on_events([_ev(b"lk:0000")], watermark=eng.version())
        assert _wait(
            lambda: m.ready()
            and m.backend_level() == 1
            and m.staleness() == 0
        ), f"never contained at single-device (rung {m.backend_level()})"
        assert m.published_root_hex() == _golden_root(eng)
        v1 = m.published_version()
        assert v1 >= v0, "version stamp went backwards across degrade"
        kinds = [e.kind for e in get_recorder().last(100)]
        assert "device_degraded" in kinds
    finally:
        inj.heal()

    assert _wait(lambda: m.backend_level() == 8), "never reclimbed"
    kinds = [e.kind for e in get_recorder().last(100)]
    assert "device_healed" in kinds
    eng.set(b"lk:0001", b"AFTERHEAL")
    m.on_events([_ev(b"lk:0001")], watermark=eng.version())
    assert _wait(
        lambda: m.staleness() == 0
        and m.published_root_hex() == _golden_root(eng)
    )
    assert m.published_version() >= v1
    inj.uninstall()
    m.close()


def test_hang_injection_pump_alive_queries_never_block():
    """The rc=124 shape, contained: a dispatch wedged past the deadline is
    abandoned — queries keep answering the published snapshot instantly,
    the pump thread survives, and the ladder lands on the surviving
    backend."""
    eng = _engine()
    m = _mirror(eng, sharding="8", dispatch_deadline_ms=400)
    with DeviceFaultInjector(match="shard*", mode="hang", hang_s=1.2):
        _warm(m)  # warm itself rides the ladder through the hang
        assert m.backend_level() == 1
        # Stage into a now-clean backend; then hang only sharded widths,
        # so serving stays live while heal probes keep timing out.
        eng.set(b"lk:0002", b"HUNG")
        m.on_events([_ev(b"lk:0002")], watermark=eng.version())
        t0 = time.perf_counter()
        root = m.published_root_hex()
        dt = time.perf_counter() - t0
        assert root is not None
        assert dt < 0.35, f"query waited {dt:.3f}s (deadline is 0.4s)"
        assert _wait(lambda: m.staleness() == 0, timeout=30)
        assert m.published_root_hex() == _golden_root(eng)
        assert m._pump_thread is not None and m._pump_thread.is_alive()
    assert _wait(lambda: m.backend_level() == 8, timeout=60)
    assert m.published_root_hex() == _golden_root(eng)
    time.sleep(1.3)  # let abandoned guard workers drain before teardown
    m.close()


def test_scrub_detects_silent_corruption_and_repairs():
    eng = _engine()
    m = _mirror(eng, sharding="8", degrade_after=3)
    _warm(m)
    m._scrub_keys = 1 << 20  # whole-keyspace sample: deterministic hit
    assert _wait(lambda: m.staleness() == 0, timeout=30)
    assert m.scrub_once() is True, "clean tree must scrub clean"

    inj = DeviceFaultInjector(match="shard*scatter", mode="corrupt")
    with inj:
        eng.set(b"lk:0003", b"CORRUPT")
        m.on_events([_ev(b"lk:0003")], watermark=eng.version())
        assert _wait(lambda: m.staleness() == 0 and inj.corruptions > 0,
                     timeout=30)
        inj.heal()
        assert m.scrub_once() is False, "scrub missed the flipped leaf"
    # invalidate + rebuild repaired it; the scrub counters moved.
    assert _wait(
        lambda: m.ready() and m.published_root_hex() == _golden_root(eng)
    )
    from merklekv_tpu.obs.metrics import get_metrics

    counters = get_metrics().snapshot()["counters"]
    assert counters.get("device.scrub_mismatches", 0) >= 1
    from merklekv_tpu.obs.flightrec import get_recorder

    assert any(
        e.kind == "device_corruption" for e in get_recorder().last(100)
    )
    m.close()


def test_invalidate_leaves_fallback_heartbeat_not_silence():
    """The PR small fix: a node serving off the native fallback after
    invalidate() must heartbeat into the flight timeline (one event per
    10 s window), driven by the same gauge poll the flight sampler runs."""
    from merklekv_tpu.obs.flightrec import get_recorder

    eng = _engine()
    m = _mirror(eng, sharding="off")
    _warm(m)
    base = sum(
        1 for e in get_recorder().last(200) if e.kind == "device_fallback"
    )
    m.invalidate()
    m.pump_lag_ms()  # the sampler's 1 s gauge poll path
    m.pump_lag_ms()  # second poll inside the window: no duplicate
    beats = [
        e for e in get_recorder().last(200) if e.kind == "device_fallback"
    ]
    assert len(beats) == base + 1, "exactly one heartbeat per flag window"
    assert beats[-1].fields.get("rung") is not None
    m.close()


def test_node_metrics_backend_level_line_rendered_in_top():
    """The device.backend_level METRICS line parses into top's BKND
    column (and absent lines render '-' for pre-ladder nodes)."""
    from merklekv_tpu.obs.top import NodeSample, render_table

    s = NodeSample(node="n1", ok=True, unix=1.0)
    s.backend_level = 1
    old = NodeSample(node="n2", ok=True, unix=1.0)  # pre-ladder node
    table = render_table({}, {"n1": s, "n2": old})
    lines = table.splitlines()
    header = lines[0].split()
    idx = header.index("BKND")
    n1_row = [ln for ln in lines if ln.startswith("n1")][0].split()
    n2_row = [ln for ln in lines if ln.startswith("n2")][0].split()
    assert n1_row[idx] == "1"
    assert old.backend_level == -2 and n2_row[idx] == "-"


@pytest.mark.slow
def test_soak_repeated_inject_heal_cycles_no_thread_leak():
    """Repeated fault/heal cycles: every cycle degrades to the surviving
    backend and reclimbs bit-identically; thread count stays bounded (no
    leaked pump/warm/guard workers)."""
    eng = _engine()
    m = _mirror(eng, sharding="8")
    _warm(m)
    baseline_threads = threading.active_count()
    for cycle in range(4):
        inj = DeviceFaultInjector(match="shard*", mode="fail").install()
        try:
            key = b"lk:%04d" % (cycle % N_KEYS)
            eng.set(key, b"soak%d" % cycle)
            m.on_events([_ev(key)], watermark=eng.version())
            assert _wait(
                lambda: m.ready()
                and m.backend_level() == 1
                and m.staleness() == 0
            ), f"cycle {cycle}: never contained"
            assert m.published_root_hex() == _golden_root(eng)
        finally:
            inj.heal()
        assert _wait(lambda: m.backend_level() == 8), (
            f"cycle {cycle}: never reclimbed"
        )
        assert _wait(
            lambda: m.published_root_hex() == _golden_root(eng)
        )
        inj.uninstall()
    # Warm/pump/guard threads are reused or reaped — a few in flight is
    # fine, monotone growth is the leak this guards against.
    assert threading.active_count() <= baseline_threads + 4, (
        f"thread leak: {baseline_threads} -> {threading.active_count()}"
    )
    m.close()

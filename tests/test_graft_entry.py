"""Driver entry-point contract tests.

``entry()`` must return a jittable fn + args; ``dryrun_multichip(n)`` must
succeed even when the current process has fewer than n devices (it re-execs
into a subprocess that provisions a virtual n-device CPU mesh — the fix for
round 1's red MULTICHIP gate).
"""

import os
import sys

import jax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__  # noqa: E402


def test_entry_jits():
    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)


def test_dryrun_multichip_inline():
    # conftest provisions 8 virtual CPU devices, so this runs in-process.
    __graft_entry__.dryrun_multichip(8)


@pytest.mark.slow
def test_dryrun_multichip_subprocess():
    # More devices than this process has -> must delegate to a subprocess
    # that self-provisions the larger virtual mesh.
    n = len(jax.devices()) * 2
    __graft_entry__.dryrun_multichip(n)

"""Driver entry-point contract tests.

``entry()`` must return a jittable fn + args; ``dryrun_multichip(n)`` must
succeed even when the current process has fewer than n devices (it re-execs
into a subprocess that provisions a virtual n-device CPU mesh — the fix for
round 1's red MULTICHIP gate).
"""

import os
import sys

import jax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__  # noqa: E402


def test_entry_jits():
    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)


def test_dryrun_multichip_inline():
    # conftest provisions 8 virtual CPU devices, so this runs in-process.
    __graft_entry__.dryrun_multichip(8)


@pytest.mark.slow
def test_dryrun_multichip_subprocess():
    # More devices than this process has -> must delegate to a subprocess
    # that self-provisions the larger virtual mesh.
    n = len(jax.devices()) * 2
    __graft_entry__.dryrun_multichip(n)


def test_multichip_phase_breadcrumbs(tmp_path, monkeypatch, capsys):
    """The probe leaves per-phase breadcrumbs: flushed stderr lines (the
    driver's tail capture names the last phase even on a timeout kill)
    and, with MKV_PHASE_FILE set, an incrementally rewritten JSON sidecar
    with per-phase wall times."""
    import json

    phase_file = tmp_path / "phases.json"
    monkeypatch.setenv("MKV_PHASE_FILE", str(phase_file))
    __graft_entry__.dryrun_multichip(8)
    err = capsys.readouterr().err
    assert "# MULTICHIP PHASE mesh-init" in err
    assert "# MULTICHIP PHASE spmd-jit-run" in err
    assert "# MULTICHIP PHASE done" in err
    doc = json.loads(phase_file.read_text())
    names = [p["phase"] for p in doc["phases"]]
    assert names.index("mesh-init") < names.index("spmd-jit-run")
    assert "serving-tree" in names
    # Every completed phase carries its wall time.
    assert all("seconds" in p for p in doc["phases"])


def test_error_kind_classification():
    """MULTICHIP_r01's death ("need 8 devices, have 1") must classify as an
    ENVIRONMENT failure — the driver's weather, not a code regression — so
    bench triage and blackbox stop paging on device-complement shortfalls.
    Assertion failures from the probe's own math stay "code"."""
    env = __graft_entry__._classify_error
    assert env("RuntimeError: need 8 devices, have 1") == "environment"
    assert env("mesh needs 8 devices, have 1") == "environment"
    assert env("Unable to initialize backend 'tpu'") == "environment"
    assert env("DEADLINE_EXCEEDED: rpc timed out") == "environment"
    assert env("watchdog: 240s deadline expired in phase 'mesh-init'") == (
        "environment"
    )
    assert env("AssertionError: sharded root != single-device root") == "code"
    assert env("TypeError: unsupported operand") == "code"


def test_device_count_flight_event_precedes_mesh_init(tmp_path, monkeypatch):
    """The probe records the delivered device complement (want/have) as a
    phase breadcrumb BEFORE mesh init — and the enumerate breadcrumb lands
    BEFORE the first backend touch, so a hang inside jax.devices() is
    attributed to enumeration, not its predecessor."""
    import json

    phase_file = tmp_path / "phases.json"
    monkeypatch.setenv("MKV_PHASE_FILE", str(phase_file))
    __graft_entry__.dryrun_multichip(8)
    doc = json.loads(phase_file.read_text())
    by_name = {p["phase"]: p for p in doc["phases"]}
    names = [p["phase"] for p in doc["phases"]]
    assert names.index("device-enumerate") < names.index("device-count")
    assert names.index("device-count") < names.index("mesh-init")
    assert by_name["device-count"]["want"] == 8
    assert by_name["device-count"]["have"] >= 8


def test_watchdog_exits_with_sidecar_and_record(tmp_path):
    """A hung probe must die by the INTERNAL watchdog, not the driver's
    rc=124 kill: exit 3, a partial JSON record on stdout naming the stuck
    phase, and the phase sidecar closed out with a watchdog-timeout entry
    (MULTICHIP_r01-r05 all died rc=124 with only a stderr tail)."""
    import json
    import subprocess

    phase_file = tmp_path / "phases.json"
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = "\n".join(
        [
            "import os, sys, time",
            "os.environ['MKV_MULTICHIP_DEADLINE_S'] = '1'",
            f"os.environ['MKV_PHASE_FILE'] = {str(phase_file)!r}",
            f"sys.path.insert(0, {root!r})",
            "import __graft_entry__ as g",
            "g._start_watchdog()",
            "g._phase('mesh-init-sim')",
            "time.sleep(60)  # simulated hang: never reaches 'done'",
        ]
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=30,
    )
    assert out.returncode == 3, (out.returncode, out.stderr[-1000:])
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["ok"] is False
    assert "mesh-init-sim" in rec["error"]
    # A watchdog timeout is tunnel/backend weather, not a regression.
    assert rec["error_kind"] == "environment"
    assert any(p["phase"] == "mesh-init-sim" for p in rec["phases"])
    doc = json.loads(phase_file.read_text())
    names = [p["phase"] for p in doc["phases"]]
    assert "watchdog-timeout" in names
    # The stuck phase's elapsed time was closed out by the final rewrite.
    stuck = [p for p in doc["phases"] if p["phase"] == "mesh-init-sim"]
    assert stuck and "seconds" in stuck[0]


def test_watchdog_record_names_the_partition(tmp_path):
    """Partitioned runs (MKV_PARTITION_ID set) stamp the active partition
    on every phase breadcrumb, the watchdog's JSON record, and the
    MULTICHIP_FLIGHT.bin dump — a stuck phase then names WHICH
    partition's mesh wedged, not just which phase (the r05-class blind
    timeout, scoped)."""
    import json
    import subprocess

    phase_file = tmp_path / "phases.json"
    flight_file = tmp_path / "MULTICHIP_FLIGHT.bin"
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = "\n".join(
        [
            "import os, sys, time",
            "os.environ['MKV_MULTICHIP_DEADLINE_S'] = '1'",
            "os.environ['MKV_PARTITION_ID'] = '3'",
            f"os.environ['MKV_PHASE_FILE'] = {str(phase_file)!r}",
            f"os.environ['MKV_FLIGHT_FILE'] = {str(flight_file)!r}",
            f"sys.path.insert(0, {root!r})",
            "import __graft_entry__ as g",
            "g._start_watchdog()",
            "g._phase('mesh-init-sim')",
            "time.sleep(60)  # simulated hang",
        ]
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=30,
    )
    assert out.returncode == 3, (out.returncode, out.stderr[-1000:])
    # The stderr breadcrumb names the partition inline.
    assert "# MULTICHIP PHASE mesh-init-sim partition=3" in out.stderr
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["partition"] == 3
    assert all(p.get("partition") == 3 for p in rec["phases"])
    # The flight dump attributes its events to the partitioned probe.
    from merklekv_tpu.obs.flightrec import read_spill

    doc = read_spill(str(flight_file))
    assert doc.node == "multichip-probe-p3"
    kinds = [e.kind for e in doc.events]
    assert "multichip_phase" in kinds
    assert any(
        e.fields.get("partition") in (3, "3") for e in doc.events
    )

"""Request plane (merklekv_tpu/requestplane/): the pooled epoll router
with hot-key read leases.

Covers the PR-17 contracts end to end:

- LeaseCache unit behavior: one fill per missed key (leader + waiting
  herd), lease steal after timeout, LRU byte budget, max-age expiry,
  targeted and partition-wide invalidation.
- InvalidationFeed unit behavior: per-key event drops, hseq-gap
  partition flush, TRUNCATE flush, decode-error tolerance.
- Router io plane: full client-side pipelining with byte-boundary fuzz
  (responses byte-identical and strictly ordered no matter how requests
  are chunked), fan-out merges byte-identical to the smart client's
  view, upstream death surfacing as the TYPED retryable BUSY error with
  zero cross-command desync.
- The cached-read staleness contract: a FaultInjector-dropped
  invalidation frame can leave a stale cached answer, but NEVER one
  staler than its ``vs=`` stamp's bound, and the router heals within the
  documented window (docs/PROTOCOL.md "Router semantics",
  docs/FAULT_MODEL.md "Request-plane failures").
- Observability parity: /healthz + Prometheus exporter on the router.
- The router-through-replica-kill chaos drill (CI integration sweep).
"""

from __future__ import annotations

import json
import random
import socket
import threading
import time
import urllib.request
import uuid

import pytest

from merklekv_tpu.client import (
    MerkleKVClient,
    PartitionedClient,
    ProtocolError,
    ReadOnlyError,
    ServerBusyError,
)
from merklekv_tpu.cluster.change_event import (
    ChangeEvent,
    OpKind,
    encode_batch_cbor,
)
from merklekv_tpu.cluster.node import ClusterNode
from merklekv_tpu.cluster.transport import TcpBroker
from merklekv_tpu.config import Config
from merklekv_tpu.native_bindings import NativeEngine, NativeServer
from merklekv_tpu.requestplane import (
    LEAD,
    WAIT,
    InvalidationFeed,
    LeaseCache,
    RequestPlaneRouter,
)
from merklekv_tpu.testing.faults import FaultInjector
from merklekv_tpu.utils.tracing import get_metrics


def _free_ports(n: int) -> list[int]:
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class MiniCluster:
    """P partitions x R replicas of in-process ClusterNodes, optionally
    replicating per partition over one shared TcpBroker."""

    def __init__(
        self, partitions: int, replicas: int = 1, replicated: bool = False
    ) -> None:
        self.partitions = partitions
        self.replicas = replicas
        self.broker = TcpBroker() if replicated else None
        self.topic = f"rplane-{uuid.uuid4().hex[:8]}"
        ports = _free_ports(partitions * replicas)
        self.addr = [
            [f"127.0.0.1:{ports[p * replicas + r]}" for r in range(replicas)]
            for p in range(partitions)
        ]
        self.spec = ";".join(
            f"{p}=" + ",".join(self.addr[p]) for p in range(partitions)
        )
        self.engines: dict[tuple[int, int], NativeEngine] = {}
        self.servers: dict[tuple[int, int], NativeServer] = {}
        self.nodes: dict[tuple[int, int], ClusterNode] = {}
        for p in range(partitions):
            for r in range(replicas):
                self.start_node(p, r)

    def _cfg(self, pid: int, r: int) -> Config:
        cfg = Config()
        cfg.host = "127.0.0.1"
        cfg.port = int(self.addr[pid][r].rsplit(":", 1)[1])
        cfg.cluster.partitions = self.partitions
        cfg.cluster.partition_id = pid
        cfg.cluster.partition_map = self.spec
        if self.broker is not None:
            cfg.replication.enabled = True
            cfg.replication.mqtt_broker = self.broker.host
            cfg.replication.mqtt_port = self.broker.port
            cfg.replication.topic_prefix = self.topic
        cfg.anti_entropy.enabled = False
        return cfg

    def start_node(self, pid: int, r: int) -> None:
        key = (pid, r)
        eng = self.engines.get(key)
        if eng is None:
            eng = NativeEngine("mem")
            self.engines[key] = eng
        port = int(self.addr[pid][r].rsplit(":", 1)[1])
        srv = NativeServer(eng, "127.0.0.1", port)
        srv.start()
        node = ClusterNode(self._cfg(pid, r), eng, srv)
        node.start()
        self.servers[key] = srv
        self.nodes[key] = node

    def kill(self, pid: int, r: int) -> None:
        key = (pid, r)
        node = self.nodes.pop(key, None)
        if node is not None:
            node.stop()
        srv = self.servers.pop(key, None)
        if srv is not None:
            srv.close()

    @property
    def flat_addrs(self) -> list[str]:
        return [a for group in self.addr for a in group]

    def close(self) -> None:
        for key in list(self.nodes):
            self.kill(*key)
        for eng in self.engines.values():
            try:
                eng.close()
            except Exception:
                pass
        self.engines.clear()
        if self.broker is not None:
            self.broker.close()


@pytest.fixture
def cluster2():
    c = MiniCluster(2, 1)
    yield c
    c.close()


def _start_router(cluster: MiniCluster, **kw) -> RequestPlaneRouter:
    seeds = kw.pop("seeds", cluster.flat_addrs)
    return RequestPlaneRouter("127.0.0.1", 0, seeds, **kw).start()


def _counter(name: str) -> int:
    return int(get_metrics().snapshot()["counters"].get(name, 0))


def _direct(addr: str, **kw) -> MerkleKVClient:
    host, port = addr.rsplit(":", 1)
    return MerkleKVClient(host, int(port), **kw)


# -- LeaseCache units --------------------------------------------------------
def test_lease_cache_fill_hit_invalidate():
    cache = LeaseCache(10_000, max_age_ms=60_000)
    calls = []
    res = cache.begin_get("k", 0, calls.append)
    assert res is LEAD
    assert cache.finish_fill("k", "v1", 0) == []
    value, age_ms = cache.begin_get("k", 0, calls.append)
    assert value == "v1" and age_ms >= 0.0
    assert cache.keys == 1 and cache.bytes_used > 0
    assert cache.invalidate("k") is True
    assert cache.invalidate("k") is False  # already gone
    assert cache.begin_get("k", 0, calls.append) is LEAD
    assert calls == []  # hits and leads never enqueue the waiter


def test_lease_cache_single_fill_under_herd():
    cache = LeaseCache(10_000)
    got: list[tuple] = []

    def waiter(value, age_ms, error):
        got.append((value, error))

    assert cache.begin_get("hot", 3, waiter) is LEAD
    for _ in range(5):
        assert cache.begin_get("hot", 3, waiter) is WAIT
    assert cache.leases_inflight == 1
    waiters = cache.finish_fill("hot", "V", 3)
    assert len(waiters) == 5
    for w in waiters:
        w("V", 0.0, None)
    assert got == [("V", None)] * 5
    # A failed fill releases the lease and caches nothing.
    assert cache.begin_get("bad", 0, waiter) is LEAD
    assert cache.begin_get("bad", 0, waiter) is WAIT
    waiters = cache.finish_fill("bad", None, 0, error="ERROR boom\r\n")
    assert len(waiters) == 1
    assert cache.begin_get("bad", 0, waiter) is LEAD  # lease released


def test_lease_cache_steal_after_timeout():
    cache = LeaseCache(10_000, lease_timeout_ms=30.0)
    herd: list = []
    assert cache.begin_get("k", 0, herd.append) is LEAD
    assert cache.begin_get("k", 0, herd.append) is WAIT
    time.sleep(0.06)
    # The stuck leader's lease is stolen; the queued waiter survives.
    assert cache.begin_get("k", 0, herd.append) is LEAD
    waiters = cache.finish_fill("k", "v", 0)
    assert len(waiters) == 1


def test_lease_cache_budget_eviction_and_partition_flush():
    cache = LeaseCache(1200, max_age_ms=60_000)
    for i in range(20):
        assert cache.begin_get(f"k{i:02d}", i % 2, lambda *a: None) is LEAD
        cache.finish_fill(f"k{i:02d}", "x" * 20, i % 2)
    assert cache.bytes_used <= 1200
    assert cache.keys < 20  # LRU evicted the overflow
    # The newest entry survived; flushing its partition drops it.
    assert cache.begin_get("k19", 1, lambda *a: None) not in (LEAD, WAIT)
    flushed = cache.flush_partition(1)
    assert flushed >= 1
    assert cache.begin_get("k19", 1, lambda *a: None) is LEAD


def test_lease_cache_max_age_expiry():
    cache = LeaseCache(10_000, max_age_ms=30.0)
    assert cache.begin_get("k", 0, lambda *a: None) is LEAD
    cache.finish_fill("k", "v", 0)
    hit = cache.begin_get("k", 0, lambda *a: None)
    assert hit not in (LEAD, WAIT)
    time.sleep(0.05)
    assert cache.begin_get("k", 0, lambda *a: None) is LEAD  # expired


# -- InvalidationFeed units --------------------------------------------------
class _FakeTransport:
    def __init__(self):
        self.subs: list[tuple[str, object]] = []

    def subscribe(self, prefix, cb):
        self.subs.append((prefix, cb))

    def unsubscribe(self, cb):
        self.subs = [(p, c) for p, c in self.subs if c is not cb]


def _frame(keys: list[str], src: str, hseq: int,
           op: OpKind = OpKind.SET) -> bytes:
    events = [
        ChangeEvent(op=op, key=k, val=b"v", ts=time.time_ns(), src=src)
        for k in keys
    ]
    return encode_batch_cbor(events, src, hwm_seq=hseq,
                             hwm_ts=time.time_ns())


def test_invalidation_feed_events_gap_and_truncate():
    cache = LeaseCache(100_000, max_age_ms=60_000)
    tr = _FakeTransport()
    feed = InvalidationFeed(cache, tr, "pref")
    assert tr.subs and tr.subs[0][0] == "pref/"
    cb = tr.subs[0][1]

    def fill(key, pid):
        assert cache.begin_get(key, pid, lambda *a: None) is LEAD
        cache.finish_fill(key, "v", pid)

    for k in ("a0", "b0", "c0"):
        fill(k, 0)
    fill("z1", 1)
    # Contiguous frame: only the named key drops.
    cb("pref/p0/events", _frame(["a0"], "n1", hseq=1))
    assert cache.begin_get("a0", 0, lambda *a: None) is LEAD
    hit = cache.begin_get("b0", 0, lambda *a: None)
    assert hit not in (LEAD, WAIT)
    # hseq jump beyond this frame's batch: missed invalidations — the
    # whole partition flushes, other partitions untouched.
    gap0 = _counter("router.inval_gap_flushes")
    cb("pref/p0/events", _frame(["c0"], "n1", hseq=9))
    assert _counter("router.inval_gap_flushes") == gap0 + 1
    assert cache.begin_get("b0", 0, lambda *a: None) is LEAD
    assert cache.begin_get("z1", 1, lambda *a: None) not in (LEAD, WAIT)
    # TRUNCATE is keyspace-wide: partition flush.
    fill("d1", 1)
    cb("pref/p1/events", _frame(["ignored"], "n2", hseq=1,
                                op=OpKind.TRUNCATE))
    assert cache.begin_get("d1", 1, lambda *a: None) is LEAD
    # Garbage payloads count, never raise.
    bad0 = _counter("router.inval_decode_errors")
    cb("pref/p0/events", b"\xff\x00not-cbor")
    assert _counter("router.inval_decode_errors") == bad0 + 1
    # Non-event topics are ignored.
    cb("pref/p0/forward", _frame(["b0"], "n1", hseq=10))
    feed.close()
    assert tr.subs == []


# -- io plane: pipelining, merges, fuzz --------------------------------------
def _sock_lines(sock: socket.socket, n: int, timeout: float = 15.0) -> bytes:
    """Read exactly n response lines (VALUES/KEYS blocks count their rows
    as part of the SAME logical response via the caller's n)."""
    sock.settimeout(timeout)
    buf = bytearray()
    while buf.count(b"\n") < n:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("router closed mid-read")
        buf += chunk
    return bytes(buf)


def test_router_merges_byte_identical_to_smart_client(cluster2):
    router = _start_router(cluster2)
    try:
        data = {f"mk{i:02d}": f"val{i}" for i in range(12)}
        ask = list(data) + ["ghost"]
        with PartitionedClient(cluster2.flat_addrs) as smart:
            for k, v in data.items():
                smart.set(k, v)
            smart_mget = smart.mget(ask)
        with MerkleKVClient("127.0.0.1", router.port) as via:
            assert via.mget(ask) == smart_mget
            # DBSIZE fans out and sums the per-partition counts.
            assert via.dbsize() == len(data)
            assert via.exists(*data, "ghost") == len(data)
            assert sorted(via.scan("mk")) == sorted(data)
            via.mset({"mm1": "a", "mm2": "b"})
            assert via.get("mm1") == "a" and via.get("mm2") == "b"
        # Raw wire shape: request-order rows, exact found count.
        keys = list(data)[:3] + ["ghost"] + list(data)[3:5]
        expected = f"VALUES {5}\r\n" + "".join(
            f"{k} {data.get(k, 'NOT_FOUND')}\r\n" for k in keys
        )
        with socket.create_connection(("127.0.0.1", router.port)) as s:
            s.sendall(("MGET " + " ".join(keys) + "\r\n").encode())
            got = _sock_lines(s, 1 + len(keys))
        assert got == expected.encode()
        # All-miss MGET collapses to the protocol's bare NOT_FOUND.
        with socket.create_connection(("127.0.0.1", router.port)) as s:
            s.sendall(b"MGET ghost1 ghost2\r\n")
            assert _sock_lines(s, 1) == b"NOT_FOUND\r\n"
    finally:
        router.stop()


def test_router_pipelined_fuzz_byte_boundaries(cluster2):
    """The ordering contract under hostile framing: a seeded stream of
    singles and fan-outs, sent with requests split at arbitrary byte
    boundaries (including mid-line), must produce the byte-exact response
    stream in strict request order."""
    router = _start_router(cluster2)
    try:
        rng = random.Random(7)
        vals = {f"fz{i:03d}": f"w{i * 17 % 101:03d}" for i in range(40)}
        with MerkleKVClient("127.0.0.1", router.port) as c:
            for k, v in vals.items():
                c.set(k, v)
        reqs: list[bytes] = []
        expected = bytearray()
        for _ in range(300):
            kind = rng.random()
            ks = rng.sample(list(vals), rng.randint(1, 5))
            if kind < 0.35:  # GET
                reqs.append(f"GET {ks[0]}\r\n".encode())
                expected += f"VALUE {vals[ks[0]]}\r\n".encode()
            elif kind < 0.55:  # SET to the key's fixed value (idempotent)
                reqs.append(f"SET {ks[0]} {vals[ks[0]]}\r\n".encode())
                expected += b"OK\r\n"
            elif kind < 0.75:  # MGET fan-out between singles
                reqs.append(("MGET " + " ".join(ks) + "\r\n").encode())
                expected += f"VALUES {len(ks)}\r\n".encode()
                expected += "".join(
                    f"{k} {vals[k]}\r\n" for k in ks
                ).encode()
            elif kind < 0.9:  # EXISTS fan-out
                reqs.append(("EXISTS " + " ".join(ks) + "\r\n").encode())
                expected += f"EXISTS {len(ks)}\r\n".encode()
            else:  # local PING rides the same ordered queue
                reqs.append(f"PING t{len(reqs)}\r\n".encode())
                expected += f"PONG t{len(reqs) - 1}\r\n".encode()
        blob = b"".join(reqs)
        with socket.create_connection(("127.0.0.1", router.port)) as s:
            def feeder():
                i = 0
                while i < len(blob):
                    step = rng.choice((1, 2, 3, 7, 50, 400))
                    s.sendall(blob[i:i + step])
                    i += step
                    if rng.random() < 0.05:
                        time.sleep(0.002)

            t = threading.Thread(target=feeder, daemon=True)
            t.start()
            got = _sock_lines(s, expected.count(b"\n"), timeout=60.0)
            t.join()
        assert got == bytes(expected)
    finally:
        router.stop()


def test_router_refuses_oversized_line(cluster2):
    router = _start_router(cluster2)
    try:
        with socket.create_connection(("127.0.0.1", router.port)) as s:
            s.sendall(b"GET " + b"x" * (2 << 20) + b"\r\n")
            got = _sock_lines(s, 1)
            assert got.startswith(b"ERROR line too long")
            # The connection closes after the refusal flushes (EOF, or
            # RST when the kernel still holds unread oversized input).
            s.settimeout(5.0)
            try:
                assert s.recv(1024) == b""
            except ConnectionResetError:
                pass
    finally:
        router.stop()


def test_router_unsupported_verb_and_validation(cluster2):
    router = _start_router(cluster2)
    try:
        with socket.create_connection(("127.0.0.1", router.port)) as s:
            s.sendall(b"FLUSHALL\r\nSET lonely\r\nINC k notanumber\r\n")
            got = _sock_lines(s, 3).decode().splitlines()
        assert "unsupported verb" in got[0]
        assert got[1] == "ERROR SET command requires a key and value"
        assert got[2] == "ERROR INC command amount must be a valid number"
    finally:
        router.stop()


def test_router_upstream_kill_typed_retryable_error(cluster2):
    """Killing a partition's only backend surfaces the TYPED retryable
    BUSY error for that partition — while the OTHER partition keeps
    answering on the SAME client connection (no desync, no close)."""
    router = _start_router(cluster2, timeout=2.0)
    try:
        pmap = router.map
        k0 = next(
            f"p0k{i}" for i in range(100)
            if pmap.partition_for_key(f"p0k{i}") == 0
        )
        k1 = next(
            f"p1k{i}" for i in range(100)
            if pmap.partition_for_key(f"p1k{i}") == 1
        )
        with MerkleKVClient("127.0.0.1", router.port, timeout=30.0) as c:
            c.set(k0, "a")
            c.set(k1, "b")
            resets0 = _counter("router.upstream_resets")
            cluster2.kill(1, 0)
            with pytest.raises(ServerBusyError):
                c.get(k1)
            # Same connection, surviving partition: still perfect.
            assert c.get(k0) == "a"
            with pytest.raises(ServerBusyError):
                c.set(k1, "c")
            assert c.get(k0) == "a"
        assert _counter("router.upstream_resets") > resets0
    finally:
        router.stop()


# -- lease cache through the router ------------------------------------------
def test_router_cache_serves_hits_and_invalidates_on_events():
    cluster = MiniCluster(2, 1, replicated=True)
    router = None
    try:
        router = _start_router(
            cluster, cache_bytes=50_000, cache_max_age_ms=30_000.0,
            broker=cluster.broker.host, broker_port=cluster.broker.port,
            topic_prefix=cluster.topic,
        )
        with MerkleKVClient("127.0.0.1", router.port, timeout=30.0) as c:
            c.set("hotkey", "v1")
            hits0 = _counter("router.cache_hits")
            assert c.get("hotkey") == "v1"  # fill
            assert c.get("hotkey") == "v1"  # hit
            assert _counter("router.cache_hits") > hits0
            # A write THROUGH the router invalidates synchronously
            # (read-your-writes on this path).
            c.set("hotkey", "v2")
            assert c.get("hotkey") == "v2"
            # A write BEHIND the router (direct to the owning node) must
            # flow back as a replication event and drop the cached entry.
            assert c.get("hotkey") == "v2"  # ensure cached
            pid = router.map.partition_for_key("hotkey")
            with _direct(cluster.addr[pid][0]) as direct:
                direct.set("hotkey", "v3")
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if c.get("hotkey") == "v3":
                    break
                time.sleep(0.05)
            assert c.get("hotkey") == "v3"
            # Stamped read: age:bound stamp parses; force-fresh bypasses.
            value, stamp = c.get_stamped("hotkey")
            assert value == "v3" and stamp is not None
            age_ms, bound_ms = stamp
            assert 0 <= age_ms <= bound_ms == 30_000
            value, _ = c.get_stamped("hotkey", force=True)
            assert value == "v3"
    finally:
        if router is not None:
            router.stop()
        cluster.close()


def test_router_staleness_never_exceeds_stamp_bound_under_dropped_frames():
    """The acceptance drill: kill the router's invalidation link, write
    behind its back, and prove every cached answer stays within its
    ``vs=`` stamp's bound — then heal the link and prove the hseq gap
    flushes the partition."""
    cluster = MiniCluster(2, 1, replicated=True)
    router = None
    inj = FaultInjector(cluster.broker.host, cluster.broker.port, seed=3)
    bound_ms = 700.0
    try:
        router = _start_router(
            cluster, cache_bytes=50_000, cache_max_age_ms=bound_ms,
            broker=inj.host, broker_port=inj.port,
            topic_prefix=cluster.topic,
        )
        with MerkleKVClient("127.0.0.1", router.port, timeout=30.0) as c:
            frames0 = _counter("router.inval_frames")
            c.set("sk", "old")
            # The write's own replication echo must land BEFORE the fill:
            # were it still in flight it would invalidate the freshly
            # cached entry and close the stale window early.
            deadline = time.monotonic() + 5.0
            while (time.monotonic() < deadline
                   and _counter("router.inval_frames") == frames0):
                time.sleep(0.02)
            assert c.get("sk") == "old"  # cached, and stable now
            # Sever the invalidation feed, then write behind the router.
            inj.kill_peer()
            pid = router.map.partition_for_key("sk")
            with _direct(cluster.addr[pid][0]) as direct:
                direct.set("sk", "new")
            wrote_at = time.monotonic()
            # While the stale window is open the stamp must bound it.
            saw_stale = False
            while True:
                value, stamp = c.get_stamped("sk")
                now = time.monotonic()
                if value == "new":
                    break
                saw_stale = True
                assert stamp is not None, "stale answer must carry a stamp"
                age_ms, b = stamp
                assert b == int(bound_ms)
                assert age_ms <= b, (
                    f"cached answer older than its bound: {age_ms} > {b}"
                )
                assert now - wrote_at < (bound_ms / 1000.0) + 5.0, (
                    "staleness window failed to close after max-age"
                )
                time.sleep(0.03)
            # The undetectable-loss window is bounded by max_age (plus
            # one poll): the documented contract.
            assert now - wrote_at <= (bound_ms / 1000.0) + 1.0
            assert saw_stale, "drill never observed the stale window"
            # Heal the link; the next event frame exposes the missed
            # hseq range and flushes the partition immediately.
            inj.revive()
            assert c.get("sk") == "new"  # re-cache
            with _direct(cluster.addr[pid][0]) as direct:
                direct.set("sk", "newer")
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if c.get("sk") == "newer":
                    break
                time.sleep(0.05)
            assert c.get("sk") == "newer"
    finally:
        if router is not None:
            router.stop()
        inj.close()
        cluster.close()


# -- observability -----------------------------------------------------------
def test_router_healthz_and_prometheus_exporter(cluster2):
    router = _start_router(cluster2, metrics_port=0)
    try:
        with MerkleKVClient("127.0.0.1", router.port) as c:
            c.set("obs", "1")
            assert c.get("obs") == "1"
            info = c.info()
            assert info.get("role") == "router"
            metrics = c.metrics()
            assert "router.commands" in metrics
            assert "router.conns" in metrics
        base = f"http://127.0.0.1:{router.metrics_port}"
        health = json.loads(
            urllib.request.urlopen(base + "/healthz", timeout=5).read()
        )
        assert health.get("role") == "router"
        assert int(health.get("workers", 0)) >= 1
        page = urllib.request.urlopen(
            base + "/metrics", timeout=5
        ).read().decode()
        assert "router" in page
    finally:
        router.stop()


# -- chaos drill (CI integration sweep) --------------------------------------
@pytest.mark.integration
def test_router_through_kill_one_replica_chaos():
    """Kill one replica of a replicated partition mid-storm, THROUGH the
    pooled router: the storm rides the typed-BUSY healing onto the
    sibling replica, per-connection ordering never desyncs, and the
    upstream reset shows on the flight metrics
    (docs/FAULT_MODEL.md "Request-plane failures")."""
    cluster = MiniCluster(2, 2, replicated=True)
    router = None
    try:
        router = _start_router(cluster, timeout=2.0)
        stop = threading.Event()
        errors: list[BaseException] = []
        model_locks = [threading.Lock() for _ in range(4)]
        models: list[dict[str, str]] = [{} for _ in range(4)]

        def storm(t: int) -> None:
            rng = random.Random(100 + t)
            try:
                with MerkleKVClient(
                    "127.0.0.1", router.port, timeout=30.0
                ) as c:
                    i = 0
                    while not stop.is_set():
                        key = f"chaos{t}_{rng.randint(0, 49):02d}"
                        try:
                            if i % 3 == 0:
                                val = f"v{t}_{i}"
                                c.set(key, val)
                                with model_locks[t]:
                                    models[t][key] = val
                            else:
                                got = c.get(key)
                                with model_locks[t]:
                                    want = models[t].get(key)
                                # A read must NEVER surface another
                                # key's value or garbage — only the
                                # model value or (transiently, around
                                # the failover) a miss.
                                if got is not None and want is not None:
                                    assert got.startswith(f"v{t}_"), got
                        except (ServerBusyError, ReadOnlyError):
                            time.sleep(0.02)  # typed retryable: back off
                        i += 1
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=storm, args=(t,), daemon=True)
            for t in range(4)
        ]
        for th in threads:
            th.start()
        time.sleep(0.6)
        resets0 = _counter("router.upstream_resets")
        cluster.kill(1, 0)  # the replica the router dialed first
        time.sleep(2.0)  # storm rides through the failover
        stop.set()
        for th in threads:
            th.join(timeout=60)
        assert not errors, errors[0]
        assert _counter("router.upstream_resets") > resets0
        # After the dust settles every surviving write reads back
        # correctly through the healed router.
        with MerkleKVClient("127.0.0.1", router.port, timeout=30.0) as c:
            c.set("post_chaos", "alive")
            assert c.get("post_chaos") == "alive"
            for t in range(4):
                sample = sorted(models[t])[-3:]
                for key in sample:
                    got = c.get(key)
                    if got is not None:
                        assert got.startswith(f"v{t}_")
    finally:
        if router is not None:
            router.stop()
        cluster.close()

"""Keyspace-sharded SERVING path (VERDICT r4 item 4).

The SPMD program is no longer a standalone demo: DeviceMerkleState accepts a
NamedSharding that places the leaf level across the device mesh (GSPMD
inserts the collectives), DeviceTreeMirror/ClusterNode expose it via
[device] sharded_mirror, and HASH on a multi-device host serves a root from
the sharded tree bit-equal to the single-device/native one. These tests run
on the virtual 8-device CPU mesh (conftest).
"""

import time
import uuid

import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from merklekv_tpu.client import MerkleKVClient
from merklekv_tpu.cluster.node import ClusterNode
from merklekv_tpu.cluster.transport import TcpBroker
from merklekv_tpu.config import Config
from merklekv_tpu.merkle.cpu import build_levels
from merklekv_tpu.merkle.encoding import leaf_hash
from merklekv_tpu.merkle.incremental import DeviceMerkleState
from merklekv_tpu.native_bindings import NativeEngine, NativeServer
from merklekv_tpu.parallel.mesh import make_mesh


def _golden_root(items: dict[bytes, bytes]) -> str:
    if not items:
        return "0" * 64
    hashes = [leaf_hash(k, v) for k, v in sorted(items.items())]
    return build_levels(hashes)[-1][0].hex()


@pytest.fixture
def sharding():
    return NamedSharding(make_mesh(), P("key", None))


def test_sharded_state_build_parity(sharding):
    items = {b"sb%04d" % i: b"val%d" % i for i in range(100)}
    st = DeviceMerkleState.from_items(items.items(), sharding=sharding)
    assert st.root_hex() == _golden_root(items)
    # The leaf level really is laid out across the mesh.
    leaf_sharding = st._levels[0].sharding
    assert not leaf_sharding.is_fully_replicated


def test_sharded_state_mutations_parity(sharding):
    items = {b"sm%04d" % i: b"v%d" % i for i in range(65)}
    st = DeviceMerkleState.from_items(items.items(), sharding=sharding)

    # Scatter path (values only).
    for i in range(9):
        items[b"sm%04d" % i] = b"upd%d" % i
    st.apply([(b"sm%04d" % i, b"upd%d" % i) for i in range(9)])
    assert st.root_hex() == _golden_root(items)
    assert st.incremental_batches >= 1

    # Restructure path (inserts + deletes, capacity growth across shards).
    for i in range(200, 300):
        items[b"sm%04d" % i] = b"new%d" % i
    del items[b"sm0007"]
    changes = [(b"sm%04d" % i, b"new%d" % i) for i in range(200, 300)]
    changes.append((b"sm0007", None))
    st.apply(changes)
    assert st.root_hex() == _golden_root(items)
    assert st.structural_batches >= 1


def test_sharded_state_small_keyspace(sharding):
    """n < number of devices: capacity is padded up to the mesh axis."""
    items = {b"tiny1": b"a", b"tiny2": b"b"}
    st = DeviceMerkleState.from_items(items.items(), sharding=sharding)
    assert st.root_hex() == _golden_root(items)
    assert st._capacity >= 8  # mesh axis size

    # Drain to empty and refill.
    st.apply([(b"tiny1", None), (b"tiny2", None)])
    assert st.root_hex() == "0" * 64
    st.apply([(b"back", b"again")])
    assert st.root_hex() == _golden_root({b"back": b"again"})


def test_cluster_node_serves_sharded_root():
    """End-to-end: a ClusterNode with [device] sharded_mirror serves HASH
    from the mesh-sharded tree, bit-equal to the native CPU root."""
    broker = TcpBroker()
    engine = NativeEngine("mem")
    server = NativeServer(engine, "127.0.0.1", 0)
    server.start()
    cfg = Config()
    cfg.replication.enabled = True
    cfg.replication.mqtt_broker = broker.host
    cfg.replication.mqtt_port = broker.port
    cfg.replication.topic_prefix = f"shard-{uuid.uuid4().hex[:8]}"
    cfg.replication.client_id = "sh1"
    cfg.device.sharded_mirror = True
    node = ClusterNode(cfg, engine, server)
    node.start()
    client = MerkleKVClient("127.0.0.1", server.port, timeout=30.0).connect()
    try:
        for i in range(48):
            client.set(f"shk{i:03d}", f"shv{i}")
        native_root = engine.merkle_root().hex()
        assert client.hash() == native_root  # native path while cold
        client.hash()  # trigger warming
        deadline = time.time() + 60
        while time.time() < deadline:
            if node._mirror is not None and node._mirror.ready():
                break
            time.sleep(0.02)
        assert node._mirror.ready(), "sharded mirror never warmed"
        # Warm path: served from the SHARDED device tree.
        assert node.device_root_hex(force=True) == native_root
        client.version_stamps = True
        client.tree_level(0, 0, 0)  # settle the stamp capability
        assert client.hash(force=True) == native_root
        leaf_sharding = node._mirror.state._levels[0].sharding
        assert not leaf_sharding.is_fully_replicated
        # Writes keep flowing through the sharded incremental path; the
        # forced HASH drains the pump so the answer is exact.
        client.set("shk000", "updated")
        assert client.hash(force=True) == engine.merkle_root().hex()
    finally:
        client.close()
        node.stop()
        server.close()
        engine.close()
        broker.close()


def test_config_parses_device_table(tmp_path):
    p = tmp_path / "c.toml"
    p.write_text("[device]\nsharded_mirror = true\n")
    assert Config.load(str(p)).device.sharded_mirror
    assert not Config().device.sharded_mirror


def test_non_pow2_shard_count_rejected():
    """Capacity is a power of two; a 3-way mesh can't divide it. The state
    rejects it loudly (the mirror meshes a pow2 device subset instead)."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    import jax

    devs = jax.devices()[:3]
    mesh = jax.sharding.Mesh(np.array(devs), ("key",))
    with pytest.raises(ValueError, match="power-of-two"):
        DeviceMerkleState(sharding=NamedSharding(mesh, P("key", None)))

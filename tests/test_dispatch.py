"""Backend dispatch: production tree paths must route through the Pallas
kernels on TPU and stay bit-identical to the scan formulation.

Round-4 VERDICT item 3: the Pallas kernels only served the bench; now
merkle/incremental.py and parallel/sharded_merkle.py route hashing through
ops/dispatch.py. On the CPU mesh the WIRING is pinned by spying on the
dispatch (full interpretation of the unrolled kernels is intractable off
TPU — see tests/test_sha256_pallas.py); the parity tests themselves run
compiled on a real chip.
"""

import numpy as np
import pytest

import jax

from merklekv_tpu.merkle.cpu import build_levels
from merklekv_tpu.merkle.encoding import leaf_hash
from merklekv_tpu.ops import dispatch

on_tpu = pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="compiled pallas kernels need TPU"
)


def _golden_root(items: dict[bytes, bytes]) -> str:
    if not items:
        return "0" * 64
    hashes = [leaf_hash(k, v) for k, v in sorted(items.items())]
    return build_levels(hashes)[-1][0].hex()


def test_dispatch_mode_selection(monkeypatch):
    monkeypatch.setenv("MKV_SHA256_BACKEND", "scan")
    assert not dispatch.use_pallas()
    monkeypatch.setenv("MKV_SHA256_BACKEND", "pallas")
    assert dispatch.use_pallas()
    monkeypatch.delenv("MKV_SHA256_BACKEND")
    assert dispatch.use_pallas() == (jax.default_backend() == "tpu")


def test_production_paths_route_through_dispatch(monkeypatch):
    """With the backend forced to "pallas", the incremental tree's build,
    scatter, and restructure programs must reach the Pallas entry points.
    The spy delegates to the scan math so the roots stay correct on CPU."""
    import merklekv_tpu.ops.sha256_pallas as sp
    from merklekv_tpu.merkle import incremental
    from merklekv_tpu.ops.sha256 import sha256_blocks, sha256_node_pairs

    calls = {"leaf": 0, "node": 0}

    def spy_leaf(blocks, nblocks, interpret=None):
        calls["leaf"] += 1
        return sha256_blocks(blocks, nblocks)

    def spy_node(left, right, interpret=None):
        calls["node"] += 1
        return sha256_node_pairs(left, right)

    def spy_level(cur, interpret=None):
        # The full build reduces levels via the adjacent-pair level kernel
        # (hash_node_level); scatter/restructure still gather explicit
        # left/right pairs through node_pairs_pallas.
        calls["node"] += 1
        p = cur.shape[0] // 2
        return sha256_node_pairs(cur[0 : 2 * p : 2], cur[1 : 2 * p : 2])

    monkeypatch.setattr(sp, "leaf_digests_pallas", spy_leaf)
    monkeypatch.setattr(sp, "node_pairs_pallas", spy_node)
    monkeypatch.setattr(sp, "node_level_pallas", spy_level)
    # Interp narrow-level fallback would bypass the node spy on CPU.
    monkeypatch.setattr(sp, "_MIN_PALLAS_PAIRS_INTERP", 0)
    monkeypatch.setenv("MKV_SHA256_BACKEND", "pallas")
    # Fresh compiled-program cache entries for the forced backend: the
    # factories key on use_pallas(), so these traces re-read the dispatch.
    incremental._build_fn.cache_clear()
    incremental._scatter_hash_fn.cache_clear()
    incremental._restructure_fn.cache_clear()

    items = {b"rk%03d" % i: b"rv%d" % i for i in range(21)}
    st = incremental.DeviceMerkleState.from_items(items.items())
    assert st.root_hex() == _golden_root(items)
    assert calls["leaf"] >= 1  # initial leaf hashing went through Pallas
    assert calls["node"] >= 1  # tree reduction went through Pallas

    # Scatter path.
    calls["node"] = 0
    items[b"rk000"] = b"changed"
    st.apply([(b"rk000", b"changed")])
    assert st.root_hex() == _golden_root(items)
    assert calls["node"] >= 1

    # Restructure path.
    calls["leaf"] = calls["node"] = 0
    items[b"rk999"] = b"inserted"
    st.apply([(b"rk999", b"inserted")])
    assert st.root_hex() == _golden_root(items)
    assert calls["node"] >= 1

    # Cleanup: drop the spy-traced programs so later tests re-trace real ones.
    incremental._build_fn.cache_clear()
    incremental._scatter_hash_fn.cache_clear()
    incremental._restructure_fn.cache_clear()


def test_sharded_step_routes_through_dispatch(monkeypatch):
    """The SPMD step's leaf hashing + local reduction honor the dispatch."""
    import merklekv_tpu.ops.sha256_pallas as sp
    from merklekv_tpu.merkle.jax_engine import leaf_digests, tree_root
    from merklekv_tpu.merkle.packing import pack_leaves
    from merklekv_tpu.ops.sha256 import (
        digest_to_bytes,
        sha256_blocks,
        sha256_node_pairs,
    )
    from merklekv_tpu.parallel import make_mesh
    from merklekv_tpu.parallel.sharded_merkle import sharded_anti_entropy_step

    calls = {"leaf": 0, "node": 0}
    monkeypatch.setattr(
        sp, "leaf_digests_pallas",
        lambda b, nb, interpret=None: (
            calls.__setitem__("leaf", calls["leaf"] + 1),
            sha256_blocks(b, nb),
        )[1],
    )
    monkeypatch.setattr(
        sp, "node_pairs_pallas",
        lambda l, r, interpret=None: (
            calls.__setitem__("node", calls["node"] + 1),
            sha256_node_pairs(l, r),
        )[1],
    )
    monkeypatch.setattr(
        sp, "node_level_pallas",
        lambda cur, interpret=None: (
            calls.__setitem__("node", calls["node"] + 1),
            sha256_node_pairs(
                cur[0 : 2 * (cur.shape[0] // 2) : 2],
                cur[1 : 2 * (cur.shape[0] // 2) : 2],
            ),
        )[1],
    )
    monkeypatch.setattr(sp, "_MIN_PALLAS_PAIRS_INTERP", 0)
    monkeypatch.setenv("MKV_SHA256_BACKEND", "pallas")

    mesh = make_mesh()  # all 8 virtual CPU devices on the "key" axis
    n = 64
    keys = [b"sk%04d" % i for i in range(n)]
    values = [b"sv%d" % i for i in range(n)]
    packed = pack_leaves(keys, values)
    digests = np.stack([np.asarray(leaf_digests(keys, values))] * 2)
    present = np.ones((2, n), bool)
    root, masks, counts = sharded_anti_entropy_step(
        mesh, packed.blocks, packed.nblocks, digests, present
    )
    monkeypatch.setenv("MKV_SHA256_BACKEND", "scan")
    expect = digest_to_bytes(np.asarray(tree_root(leaf_digests(keys, values))))
    assert digest_to_bytes(np.asarray(root)) == expect
    assert int(np.asarray(counts).sum()) == 0
    assert calls["leaf"] >= 1 and calls["node"] >= 1
    # Drop the spy-traced program so later callers re-trace the real one.
    from merklekv_tpu.parallel.sharded_merkle import _anti_entropy_program

    _anti_entropy_program.cache_clear()


# ------------------------------------------------ compiled parity (real TPU)

@on_tpu
def test_incremental_tree_parity_on_tpu():
    """DeviceMerkleState through every mutation path on the real chip (the
    default dispatch picks the compiled Pallas kernels there)."""
    from merklekv_tpu.merkle.incremental import DeviceMerkleState

    assert dispatch.use_pallas()
    items = {b"pk%04d" % i: b"pv%d" % i for i in range(4097)}
    st = DeviceMerkleState.from_items(items.items())
    assert st.root_hex() == _golden_root(items)

    for i in range(7):
        items[b"pk%04d" % i] = b"upd%d" % i
    st.apply([(b"pk%04d" % i, b"upd%d" % i) for i in range(7)])
    assert st.root_hex() == _golden_root(items)
    assert st.incremental_batches >= 1

    items[b"pk9999"] = b"new"
    del items[b"pk0003"]
    st.apply([(b"pk9999", b"new"), (b"pk0003", None)])
    assert st.root_hex() == _golden_root(items)
    assert st.structural_batches >= 1


@on_tpu
def test_sharded_step_parity_on_tpu():
    from merklekv_tpu.merkle.jax_engine import leaf_digests, tree_root
    from merklekv_tpu.merkle.packing import pack_leaves
    from merklekv_tpu.ops.sha256 import digest_to_bytes
    from merklekv_tpu.parallel import make_mesh
    from merklekv_tpu.parallel.sharded_merkle import sharded_anti_entropy_step

    mesh = make_mesh()
    d = mesh.shape["key"]
    n = d * 512
    keys = [b"sk%06d" % i for i in range(n)]
    values = [b"sv%d" % i for i in range(n)]
    packed = pack_leaves(keys, values)
    digests = np.stack([np.asarray(leaf_digests(keys, values))] * 2)
    present = np.ones((2, n), bool)
    root, masks, counts = sharded_anti_entropy_step(
        mesh, packed.blocks, packed.nblocks, digests, present
    )
    expect = digest_to_bytes(np.asarray(tree_root(leaf_digests(keys, values))))
    assert digest_to_bytes(np.asarray(root)) == expect

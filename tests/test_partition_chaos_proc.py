"""Partition chaos through REAL processes (the CI chaos step): a
2-partition x 2-replica cluster of spawned ``python -m merklekv_tpu``
nodes over a spawned broker, a write storm driven through the smart
partitioned client, and a kill -9 (PeerProcessKiller — no shutdown path,
no flush) of one replica in EVERY partition mid-storm. The storm must
ride through on the surviving replicas, the survivors must stay live,
and the respawned replicas must reconverge each partition to a
bit-identical per-partition root.
"""

import os
import socket
import subprocess
import sys
import time
import uuid

import pytest

from merklekv_tpu.client import MerkleKVClient, PartitionedClient
from merklekv_tpu.testing.faults import PeerProcessKiller

pytestmark = pytest.mark.integration

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
P, R = 2, 2


def _spawn(args):
    env = dict(os.environ, PYTHONPATH=REPO, MERKLEKV_JAX_PLATFORM="cpu")
    return subprocess.Popen(
        [sys.executable, *args],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
    )


def _port_from(proc) -> int:
    line = proc.stdout.readline()
    assert "listening on" in line, f"unexpected startup line: {line!r}"
    return int(line.rsplit(":", 1)[1].split()[0])


def _wait_port(port, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=1).close()
            return
        except OSError:
            time.sleep(0.05)
    raise TimeoutError(f"port {port} never came up")


def _free_ports(n):
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def test_kill_one_replica_per_partition_real_processes(tmp_path):
    ports = _free_ports(P * R)
    addr = [
        [f"127.0.0.1:{ports[p * R + r]}" for r in range(R)]
        for p in range(P)
    ]
    spec = ";".join(f"{p}=" + ",".join(addr[p]) for p in range(P))
    topic = f"pchaos-{uuid.uuid4().hex[:8]}"
    procs = {}
    broker = _spawn(["-m", "merklekv_tpu.broker", "--port", "0"])
    broker_port = _port_from(broker)

    def node_toml(p, r):
        cfg = tmp_path / f"node-{p}-{r}.toml"
        cfg.write_text(
            f"""
host = "127.0.0.1"
port = {ports[p * R + r]}
engine = "mem"

[cluster]
partitions = {P}
partition_id = {p}
partition_map = "{spec}"

[replication]
enabled = true
mqtt_broker = "127.0.0.1"
mqtt_port = {broker_port}
topic_prefix = "{topic}"

[anti_entropy]
engine = "cpu"
"""
        )
        return cfg

    def spawn_node(p, r):
        proc = _spawn(["-m", "merklekv_tpu", "--config",
                       str(node_toml(p, r))])
        procs[(p, r)] = proc
        port = _port_from(proc)
        _wait_port(port)
        return proc

    try:
        for p in range(P):
            for r in range(R):
                spawn_node(p, r)

        def root_of(p, r):
            host, _, port = addr[p][r].rpartition(":")
            with MerkleKVClient(host, int(port), timeout=5) as c:
                c.partition_id = p  # pt=-addressed: MOVED if misrouted
                return c.hash()

        def metrics_of(p, r):
            host, _, port = addr[p][r].rpartition(":")
            with MerkleKVClient(host, int(port), timeout=5) as c:
                return c.metrics()

        pc = PartitionedClient([addr[0][0]], timeout=5).connect()
        assert pc.map.count == P

        # Seed + wait for in-partition replication to converge, so the
        # killed replicas die holding real state.
        for i in range(120):
            pc.set(f"seed:{i:04d}", f"s{i}")
        deadline = time.time() + 30
        for p in range(P):
            while time.time() < deadline:
                if root_of(p, 0) == root_of(p, 1):
                    break
                time.sleep(0.1)
            assert root_of(p, 0) == root_of(p, 1), (
                f"partition {p} never converged pre-kill"
            )

        # The storm + the kill wave: SIGKILL replica 1 of EVERY partition
        # while writes keep flowing through the smart client (it rotates
        # to the surviving sibling on connection failure).
        killed = {
            p: PeerProcessKiller(procs.pop((p, 1))) for p in range(P)
        }
        storm_n = 300
        for i in range(storm_n):
            pc.set(f"storm:{i:04d}", f"w{i}")
            if i == 60:
                for p in range(P):
                    killed[p].kill_now()
        for p in range(P):
            assert killed[p].killed
        # Survivors never left live while their sibling was dead.
        for p in range(P):
            m = metrics_of(p, 0)
            assert m.get("partition.state") == "0", (
                f"survivor of partition {p} degraded: {m.get('partition.state')}"
            )
            assert m.get("partition.id") == str(p)
        # Every storm key is readable through the surviving replicas.
        assert all(
            pc.get(f"storm:{i:04d}") == f"w{i}" for i in range(storm_n)
        )

        # Respawn the killed replicas (fresh empty engines — a crashed
        # host came back wiped) and repair each partition from its
        # surviving sibling with one SYNC; roots must land bit-identical.
        for p in range(P):
            spawn_node(p, 1)
        for p in range(P):
            h0, _, p0 = addr[p][0].rpartition(":")
            h1, _, p1 = addr[p][1].rpartition(":")
            with MerkleKVClient(h1, int(p1), timeout=30) as c:
                assert c.sync_with(h0, int(p0))
        roots = {}
        for p in range(P):
            assert root_of(p, 0) == root_of(p, 1), (
                f"partition {p} did not reconverge after respawn"
            )
            roots[p] = root_of(p, 0)
        assert len(set(roots.values())) == P  # disjoint keyspaces
        pc.close()
    finally:
        for proc in list(procs.values()) + [broker]:
            proc.terminate()
        for proc in list(procs.values()) + [broker]:
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()

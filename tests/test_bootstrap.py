"""Elastic-membership chaos: verified snapshot shipping + delta sync.

The acceptance shape from the ISSUE: kill a 2-node cluster member, write
100K keys to the survivor, rejoin with bootstrap enabled — the joiner
converges to a bit-identical root with wire bytes well under the walk-only
rebuild (< 25%), serves zero reads before VERIFY passes, and a deliberately
corrupted donor snapshot is rejected with the joiner converging via the
second donor or the plain-walk fallback. Plus: SNAPCHUNK decode fuzzing
(every truncation offset + seeded byte flips must fail CRC cleanly — retry,
never partial-apply), slow-link resume through the bandwidth-throttle
fault, and the interior-WAL-corruption recovery path now bootstrapping
from a healthy peer.
"""

import base64
import random
import socket
import threading
import time
import zlib

import pytest

from merklekv_tpu.client import (
    ChunkIntegrityError,
    MerkleKVClient,
    MerkleKVError,
    ProtocolError,
)
from merklekv_tpu.cluster.bootstrap import BootstrapSession
from merklekv_tpu.cluster.node import ClusterNode
from merklekv_tpu.cluster.sync import SyncManager
from merklekv_tpu.config import BootstrapConfig, Config
from merklekv_tpu.native_bindings import NativeEngine, NativeServer
from merklekv_tpu.storage import DurableStore
from merklekv_tpu.storage import snapshot as snapmod
from merklekv_tpu.testing.faults import FaultInjector, corrupt_file

pytestmark = pytest.mark.integration


class Donor:
    """A running storage-backed node that can serve SNAPMETA/SNAPCHUNK."""

    def __init__(self, data_dir: str, n_keys: int = 0, key_fmt: bytes = b"k%06d"):
        self.cfg = Config()
        self.cfg.storage.enabled = True
        self.cfg.storage.merkle_engine = "cpu"
        self.cfg.anti_entropy.engine = "cpu"
        self.engine = NativeEngine("mem")
        self.storage = DurableStore(self.engine, self.cfg.storage, data_dir)
        self.storage.recover()
        self.server = NativeServer(self.engine, "127.0.0.1", 0)
        self.server.start()
        self.node = ClusterNode(self.cfg, self.engine, self.server,
                                storage=self.storage)
        self.node.start()
        for i in range(n_keys):
            self.engine.set(key_fmt % i, b"v%06d" % i)

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.server.port}"

    def close(self):
        self.node.stop()
        self.storage.stop()
        self.server.close()
        self.engine.close()


def _joiner_session(peers, chunk_bytes=65536, chunk_retries=6):
    engine = NativeEngine("mem")
    mgr = SyncManager(engine, device="cpu")
    cfg = BootstrapConfig(chunk_bytes=chunk_bytes, chunk_retries=chunk_retries)
    sess = BootstrapSession(engine, mgr, peers, cfg, merkle_engine="cpu")
    return engine, sess


def test_rejoin_bootstrap_converges_cheaper_than_walk(tmp_path):
    """The headline chaos case: a 2-node cluster member dies, the survivor
    absorbs 100K keys, and the member rejoins from nothing. Bootstrap must
    converge to a bit-identical root with wire bytes < 25% of what the
    walk-only rebuild pays for the same state."""
    donor = Donor(str(tmp_path / "a"))
    try:
        # The 2-node membership, then the death: the peer holds a few keys,
        # dies hard (no shutdown path), and its disk is gone — the
        # long-dead-replica shape.
        member = NativeEngine("mem")
        mgr0 = SyncManager(member, device="cpu")
        donor.engine.set(b"seed", b"1")
        mgr0.sync_once("127.0.0.1", donor.server.port)
        assert member.merkle_root() == donor.engine.merkle_root()
        member.close()  # kill: state discarded

        # 100K keys land on the survivor while the member is dead.
        for i in range(100_000):
            donor.engine.set(b"k%06d" % i, b"v%06d" % i)

        # Rejoin with bootstrap: snapshot shipping + delta walk.
        eng_b, sess = _joiner_session([donor.addr])
        try:
            report = sess.run("empty-keyspace")
            assert report.mode == "snapshot", report.details
            assert sess.state == "live"
            root_a = donor.engine.merkle_root()
            assert root_a is not None
            assert eng_b.merkle_root() == root_a  # bit-identical
            assert report.snapshot_items == 100_001
            boot_bytes = report.wire_bytes
            assert boot_bytes > 0
        finally:
            eng_b.close()

        # Walk-only rebuild of the identical state, for the A/B.
        eng_c = NativeEngine("mem")
        try:
            mgr = SyncManager(eng_c, device="cpu")
            rep = mgr.sync_once("127.0.0.1", donor.server.port)
            assert eng_c.merkle_root() == root_a
            walk_bytes = rep.bytes_sent + rep.bytes_received
        finally:
            eng_c.close()

        assert boot_bytes < 0.25 * walk_bytes, (
            f"bootstrap {boot_bytes}B not < 25% of walk-only {walk_bytes}B"
        )
    finally:
        donor.close()


def test_reads_blocked_until_verify_over_throttled_link(tmp_path):
    """Zero reads serve before VERIFY passes. The donor link is bandwidth-
    throttled (token-bucket fault) so the FETCH window is wide enough to
    probe: a GET against the bootstrapping node must answer ERROR LOADING,
    and the same GET serves the verified value once the session goes
    live — exercising slow-link shipping end to end."""
    donor = Donor(str(tmp_path / "a"), n_keys=8000)
    inj = FaultInjector("127.0.0.1", donor.server.port, seed=11)
    inj.set_faults("s2c", bandwidth_bytes_per_s=32 * 1024)
    eng_b = NativeEngine("mem")
    srv_b = NativeServer(eng_b, "127.0.0.1", 0)
    srv_b.start()
    cfg_b = Config()
    cfg_b.bootstrap.enabled = True
    cfg_b.bootstrap.chunk_bytes = 16384
    cfg_b.anti_entropy.peers = [f"{inj.host}:{inj.port}"]
    cfg_b.anti_entropy.engine = "cpu"
    cfg_b.storage.merkle_engine = "cpu"
    node_b = ClusterNode(cfg_b, eng_b, srv_b)
    try:
        node_b.start()
        sess = node_b.bootstrap
        assert sess is not None
        deadline = time.time() + 30
        while time.time() < deadline and sess.state not in ("fetch", "verify"):
            time.sleep(0.005)
        assert sess.state in ("fetch", "verify"), sess.state

        with MerkleKVClient("127.0.0.1", srv_b.port, timeout=5) as c:
            with pytest.raises(ProtocolError, match="LOADING"):
                c.get("k000123")

        deadline = time.time() + 60
        while time.time() < deadline and sess.state not in ("live", "failed"):
            time.sleep(0.01)
        assert sess.state == "live", (sess.state, sess.report.details)
        assert sess.report.mode == "snapshot"
        assert inj.chunks_throttled > 0

        with MerkleKVClient("127.0.0.1", srv_b.port, timeout=5) as c:
            assert c.get("k000123") == "v000123"
        assert eng_b.merkle_root() == donor.engine.merkle_root()
    finally:
        node_b.stop()
        srv_b.close()
        eng_b.close()
        inj.close()
        donor.close()


def _plant_bogus_snapshot(donor: Donor) -> None:
    """Install a NEWER snapshot whose body is valid (CRC passes, chunks
    ship cleanly) but whose stamped root is a lie — the donor-is-suspect
    case only the joiner's local verify can catch."""
    donor.storage.snapshot_now()
    snaps = snapmod.list_snapshots(donor.storage.directory)
    seq, path = snaps[-1]
    good = snapmod.read_snapshot(path)
    snapmod.write_snapshot(
        donor.storage.directory,
        seq + 1,
        good.items,
        good.tombstones,
        good.wal_seq,
        "11" * 32,  # stamped root does not match the content
    )


def test_corrupt_donor_snapshot_rejected_walk_fallback(tmp_path):
    """A donor whose newest snapshot fails stamp verification is
    quarantined; with no other donor the joiner still converges via the
    plain anti-entropy walk — and never serves the rejected state."""
    donor = Donor(str(tmp_path / "a"), n_keys=3000)
    _plant_bogus_snapshot(donor)
    eng_b, sess = _joiner_session([donor.addr])
    try:
        report = sess.run("empty-keyspace")
        assert donor.addr in report.suspects
        assert report.mode == "walk", report.details
        assert sess.state == "live"
        assert eng_b.merkle_root() == donor.engine.merkle_root()
    finally:
        eng_b.close()
        donor.close()


def test_corrupt_donor_snapshot_second_donor_serves(tmp_path):
    """Donor 1 ships garbage (stamp mismatch), donor 2 is healthy: the
    joiner quarantines the first and completes the verified transfer from
    the second."""
    bad = Donor(str(tmp_path / "a"), n_keys=3000)
    good = Donor(str(tmp_path / "b"), n_keys=3000)
    _plant_bogus_snapshot(bad)
    eng_b, sess = _joiner_session([bad.addr, good.addr])
    try:
        report = sess.run("empty-keyspace")
        assert report.suspects == [bad.addr]
        assert report.mode == "snapshot", report.details
        assert report.donor == good.addr
        assert eng_b.merkle_root() == good.engine.merkle_root()
    finally:
        eng_b.close()
        bad.close()
        good.close()


def test_mid_transfer_donor_death_fails_over(tmp_path):
    """The donor dies mid-FETCH (proxy kill after a byte budget): the
    joiner fails over to the second donor and still completes a verified
    snapshot transfer."""
    dying = Donor(str(tmp_path / "a"), n_keys=6000)
    healthy = Donor(str(tmp_path / "b"), n_keys=6000)
    inj = FaultInjector("127.0.0.1", dying.server.port, seed=3)
    # Enough budget for SNAPMETA + the first chunks, then death mid-stream.
    inj.kill_after_bytes(24 * 1024, "s2c")
    eng_b, sess = _joiner_session(
        [f"{inj.host}:{inj.port}", healthy.addr], chunk_bytes=8192,
        chunk_retries=2,
    )
    try:
        report = sess.run("empty-keyspace")
        assert report.mode == "snapshot", report.details
        assert report.donor == healthy.addr
        assert report.donor_failovers >= 1
        assert eng_b.merkle_root() == healthy.engine.merkle_root()
    finally:
        eng_b.close()
        inj.close()
        dying.close()
        healthy.close()


def test_chunk_resume_after_dropped_links(tmp_path):
    """Random stream kills (drop fault) during FETCH: per-offset retries
    reconnect and resume at the checkpoint — the transfer completes and
    the verified prefix is never refetched wholesale."""
    donor = Donor(str(tmp_path / "a"), n_keys=8000)
    inj = FaultInjector("127.0.0.1", donor.server.port, seed=1234)
    inj.set_faults("s2c", drop_rate=0.08)
    eng_b, sess = _joiner_session(
        [f"{inj.host}:{inj.port}"], chunk_bytes=8192, chunk_retries=8
    )
    try:
        report = sess.run("empty-keyspace")
        assert eng_b.merkle_root() == donor.engine.merkle_root()
        assert inj.chunks_dropped > 0
        assert report.chunk_retries > 0
        if report.mode == "snapshot":
            # Raw bytes assembled exactly once despite the retries: the
            # fetch total equals the artifact size, not a multiple of it.
            import os

            path = snapmod.snapshot_path(
                donor.storage.directory, report.snapshot_seq
            )
            assert report.bytes_fetched == os.path.getsize(path)
    finally:
        eng_b.close()
        inj.close()
        donor.close()


def test_wal_corruption_triggers_peer_bootstrap(tmp_path):
    """PR 2's interior-WAL-corruption recovery restores only a verified
    prefix and re-anchors locally; with [bootstrap] enabled the node now
    ALSO closes the data hole from a healthy peer instead of waiting out
    a worst-case walk."""
    keys = [(b"w%05d" % i, b"val%05d" % i) for i in range(400)]
    donor = Donor(str(tmp_path / "a"))
    for k, v in keys:
        donor.engine.set(k, v)

    # Build the corrupted-WAL member: journal every key, crash without a
    # shutdown snapshot, then flip a byte mid-log (interior corruption).
    cfg_b = Config()
    cfg_b.storage.enabled = True
    cfg_b.storage.merkle_engine = "cpu"
    cfg_b.storage.snapshot_on_shutdown = False
    cfg_b.storage.fsync = "never"
    b_dir = str(tmp_path / "b")
    eng_tmp = NativeEngine("mem")
    st = DurableStore(eng_tmp, cfg_b.storage, b_dir)
    st.recover()
    now = time.time_ns()
    for k, v in keys:
        st.record_set(k, v, now)
    st.stop()
    eng_tmp.close()
    from merklekv_tpu.storage import wal as walmod

    seg_path = walmod.list_segments(b_dir)[0][1]
    import os

    corrupt_file(seg_path, os.path.getsize(seg_path) // 2)

    # Restart the member: recovery reports corruption, bootstrap fires.
    eng_b = NativeEngine("mem")
    store_b = DurableStore(eng_b, cfg_b.storage, b_dir)
    report = store_b.recover()
    assert report.corruption is not None
    assert 0 < eng_b.dbsize() < len(keys)  # verified prefix only
    srv_b = NativeServer(eng_b, "127.0.0.1", 0)
    srv_b.start()
    cfg_b.bootstrap.enabled = True
    cfg_b.anti_entropy.peers = [donor.addr]
    cfg_b.anti_entropy.engine = "cpu"
    node_b = ClusterNode(cfg_b, eng_b, srv_b, storage=store_b)
    try:
        node_b.start()
        sess = node_b.bootstrap
        assert sess is not None
        deadline = time.time() + 60
        while time.time() < deadline and sess.state not in ("live", "failed"):
            time.sleep(0.01)
        assert sess.state == "live", (sess.state, sess.report.details)
        assert sess.report.reason == "wal-corruption"
        assert eng_b.merkle_root() == donor.engine.merkle_root()
    finally:
        node_b.stop()
        store_b.stop()
        srv_b.close()
        eng_b.close()
        donor.close()


def test_snapmeta_building_is_polled_not_degraded(tmp_path):
    """A donor with a live compaction ticker and no artifact yet must NOT
    block the SNAPMETA handler on an O(keyspace) snapshot write: it
    answers the transient 'building; retry' error while the background
    ticker writes the artifact, and the joiner polls it out — staying on
    the bulk path instead of degrading to the walk."""
    donor = Donor(str(tmp_path / "a"), n_keys=3000)
    donor.storage.start()  # background ticker owns the snapshot build
    try:
        with MerkleKVClient("127.0.0.1", donor.server.port) as c:
            try:
                c.snap_meta()
                polled = False  # ticker won the race — still fine
            except ProtocolError as e:
                assert "retry" in str(e).lower()
                polled = True
        eng_b, sess = _joiner_session([donor.addr])
        try:
            report = sess.run("empty-keyspace")
            assert report.mode == "snapshot", (polled, report.details)
            assert eng_b.merkle_root() == donor.engine.merkle_root()
        finally:
            eng_b.close()
    finally:
        donor.close()


def test_capability_fallback_donor_without_storage(tmp_path):
    """A peer without durable storage answers SNAPMETA with ERROR (same
    for an old-version peer without the verb): the joiner degrades to the
    plain anti-entropy walk and still converges."""
    engine = NativeEngine("mem")
    server = NativeServer(engine, "127.0.0.1", 0)
    server.start()
    node = ClusterNode(Config(), engine, server)  # no storage plane
    node.start()
    for i in range(500):
        engine.set(b"c%04d" % i, b"x%04d" % i)
    eng_b, sess = _joiner_session([f"127.0.0.1:{server.port}"])
    try:
        report = sess.run("empty-keyspace")
        assert report.mode == "walk", report.details
        assert not report.suspects  # capability miss is NOT a quarantine
        assert eng_b.merkle_root() == engine.merkle_root()
    finally:
        eng_b.close()
        node.stop()
        server.close()
        engine.close()


# ---------------------------------------------------------------- fuzzing


class _CannedServer:
    """One-shot TCP server: per connection, read one line, send the canned
    (possibly mutated) bytes, close — the smallest hostile donor."""

    def __init__(self):
        self._listener = socket.socket()
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(8)
        self.port = self._listener.getsockname()[1]
        self.payload = b""
        self._stop = False
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self):
        while not self._stop:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            try:
                conn.settimeout(2)
                buf = b""
                while not buf.endswith(b"\n"):
                    chunk = conn.recv(256)
                    if not chunk:
                        break
                    buf += chunk
                conn.sendall(self.payload)
            except OSError:
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def close(self):
        self._stop = True
        try:
            self._listener.close()
        except OSError:
            pass


def test_snapchunk_fuzz_truncations_and_bitflips():
    """Wire-path decode fuzzing (mirrors the PR 5 envelope fuzz suite):
    EVERY truncation offset of a CHUNK response, plus seeded byte flips,
    must surface as a clean client-side error — retried by the fetch loop,
    never returned as partial/corrupt data."""
    rng = random.Random(99)
    raw = bytes(rng.randrange(256) for _ in range(96))
    comp = zlib.compress(raw, 1)
    good = (
        b"CHUNK 0 %d %d\r\n" % (len(raw), zlib.crc32(raw))
        + base64.b64encode(comp)
        + b"\r\n"
    )
    srv = _CannedServer()
    try:
        def fetch():
            c = MerkleKVClient("127.0.0.1", srv.port, timeout=0.3)
            c.connect()
            try:
                return c.snap_chunk(7, 0, 4096)
            finally:
                c.close()

        srv.payload = good
        assert fetch() == raw  # the canned frame itself is sound

        for cut in range(len(good)):
            srv.payload = good[:cut]
            with pytest.raises(MerkleKVError):
                fetch()

        flips = sorted(rng.sample(range(len(good)), 48))
        for off in flips:
            srv.payload = good[:off] + bytes([good[off] ^ 0xFF]) + good[off + 1:]
            with pytest.raises(MerkleKVError):
                fetch()
    finally:
        srv.close()


def test_chunk_integrity_error_is_retryable_not_capability():
    """The error taxonomy the fetch loop depends on: integrity failures are
    ChunkIntegrityError (retry the offset), NOT ProtocolError (which would
    read as a capability miss and fail the donor)."""
    assert issubclass(ChunkIntegrityError, MerkleKVError)
    assert not issubclass(ChunkIntegrityError, ProtocolError)


# ---------------------------------------------------------------- config


def test_bootstrap_config_parse_and_validate():
    cfg = Config.from_dict(
        {"bootstrap": {"enabled": True, "chunk_bytes": 65536,
                       "chunk_retries": 2}}
    )
    assert cfg.bootstrap.enabled
    assert cfg.bootstrap.chunk_bytes == 65536
    assert cfg.bootstrap.chunk_retries == 2
    with pytest.raises(ValueError):
        Config.from_dict({"bootstrap": {"chunk_bytes": 1024}})
    with pytest.raises(ValueError):
        Config.from_dict({"bootstrap": {"chunk_retries": 0}})


def test_donor_retention_pins_snapshot_during_transfer(tmp_path):
    """Compaction during an active transfer must not delete the artifact a
    joiner is mid-fetch on: the donor pins the advertised seq until the
    pin TTL lapses."""
    donor = Donor(str(tmp_path / "a"), n_keys=2000)
    try:
        with MerkleKVClient("127.0.0.1", donor.server.port) as c:
            seq, _wal, size, _root = c.snap_meta()
            # Age the pinned snapshot behind newer compactions.
            for i in range(3):
                donor.engine.set(b"extra%d" % i, b"y")
                donor.storage.compact()
            snaps = dict(snapmod.list_snapshots(donor.storage.directory))
            assert seq in snaps, "pinned snapshot was retired mid-transfer"
            # The byte range is still fully servable.
            blob, off = b"", 0
            while off < size:
                part = c.snap_chunk(seq, off, 65536)
                blob += part
                off += len(part)
            snap = snapmod.parse_snapshot_bytes(blob)
            snapmod.verify_snapshot(snap, engine="cpu")
    finally:
        donor.close()


# ---------------------------------------------------------------- soak

@pytest.mark.slow
def test_soak_repeated_rejoin_cycles(tmp_path):
    """Rejoin soak: repeatedly kill the member, grow the survivor, rejoin
    from nothing with bootstrap — every cycle must converge bit-identically
    through the snapshot path."""
    donor = Donor(str(tmp_path / "a"))
    try:
        total = 0
        for cycle in range(4):
            for i in range(10_000):
                donor.engine.set(b"s%d:%05d" % (cycle, i), b"v%05d" % i)
            total += 10_000
            eng_b, sess = _joiner_session([donor.addr])
            try:
                report = sess.run("empty-keyspace")
                assert report.mode == "snapshot", report.details
                assert eng_b.merkle_root() == donor.engine.merkle_root()
                assert eng_b.dbsize() == total
            finally:
                eng_b.close()
    finally:
        donor.close()


@pytest.mark.slow
def test_soak_kill9_rejoin_processes(tmp_path):
    """Process-level rejoin soak: repeatedly SIGKILL the member process,
    grow the survivor, wipe the member's disk (long-dead shape), restart
    it with [bootstrap] enabled, and require converged HASH roots through
    the snapshot path every cycle."""
    import os
    import shutil
    import subprocess
    import sys

    from merklekv_tpu.testing.faults import PeerProcessKiller

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def spawn(cfg_path):
        env = dict(os.environ, PYTHONPATH=repo, MERKLEKV_JAX_PLATFORM="cpu")
        return subprocess.Popen(
            [sys.executable, "-m", "merklekv_tpu", "--config", cfg_path],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )

    def free_port():
        sk = socket.socket()
        sk.bind(("127.0.0.1", 0))
        port = sk.getsockname()[1]
        sk.close()
        return port

    def await_ready(proc, port, timeout=30):
        line = proc.stdout.readline()
        assert "listening on" in line, f"unexpected startup line: {line!r}"
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                socket.create_connection(("127.0.0.1", port), timeout=1).close()
                return
            except OSError:
                time.sleep(0.05)
        raise TimeoutError(f"port {port} never came up")

    port_a, port_b = free_port(), free_port()

    def write_cfg(path, port, peers, boot):
        peer_list = ", ".join(f'"{p}"' for p in peers)
        path.write_text(f"""
host = "127.0.0.1"
port = {port}
engine = "mem"
storage_path = "{tmp_path}"

[storage]
enabled = true
merkle_engine = "cpu"

[anti_entropy]
peers = [{peer_list}]
engine = "cpu"

[bootstrap]
enabled = {"true" if boot else "false"}
""")
        return str(path)

    cfg_a = write_cfg(tmp_path / "a.toml", port_a, [], False)
    cfg_b = write_cfg(
        tmp_path / "b.toml", port_b, [f"127.0.0.1:{port_a}"], True
    )

    procs = []
    try:
        a = spawn(cfg_a)
        procs.append(a)
        await_ready(a, port_a)
        b = spawn(cfg_b)
        procs.append(b)
        await_ready(b, port_b)

        total = 0
        for cycle in range(3):
            PeerProcessKiller(b).kill_now()  # SIGKILL: no shutdown path
            procs.remove(b)
            with MerkleKVClient("127.0.0.1", port_a, timeout=10) as c:
                batch = 500
                for base in range(0, 10_000, batch):
                    c.pipeline(
                        f"SET s{cycle}:{i:05d} v{i:05d}"
                        for i in range(base, base + batch)
                    )
                total += 10_000
                root_a = c.hash()
            # Long-dead: the member's disk is gone with the machine.
            shutil.rmtree(str(tmp_path / f"node-{port_b}"), ignore_errors=True)
            b = spawn(cfg_b)
            procs.append(b)
            await_ready(b, port_b)
            deadline = time.time() + 90
            root_b = None
            while time.time() < deadline:
                try:
                    with MerkleKVClient(
                        "127.0.0.1", port_b, timeout=5
                    ) as cb:
                        root_b = cb.hash()
                    if root_b == root_a:
                        break
                except MerkleKVError:
                    pass  # LOADING gate / mid-bootstrap: keep polling
                time.sleep(0.1)
            assert root_b == root_a, f"cycle {cycle}: never converged"
            with MerkleKVClient("127.0.0.1", port_b, timeout=5) as cb:
                assert cb.dbsize() == total
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()

"""Peer failure detection (SURVEY §5.3 — the reference has none).

PING-probe monitor state machine, the PEERS wire verb, anti-entropy
down-peer skipping, and recovery.
"""

import time
import uuid

import pytest

from merklekv_tpu.client import MerkleKVClient
from merklekv_tpu.cluster.health import PeerHealthMonitor
from merklekv_tpu.cluster.node import ClusterNode
from merklekv_tpu.cluster.sync import SyncManager
from merklekv_tpu.config import Config
from merklekv_tpu.native_bindings import NativeEngine, NativeServer


@pytest.fixture
def server():
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0)
    srv.start()
    yield eng, srv
    srv.close()
    eng.close()


def test_monitor_marks_up_and_down(server):
    eng, srv = server
    peer = f"127.0.0.1:{srv.port}"
    mon = PeerHealthMonitor([peer], timeout=0.5, down_after=2)
    assert mon.is_up(peer)  # unknown = optimistic
    mon.probe_all()
    snap = {h.peer: h for h in mon.snapshot()}
    assert snap[peer].status == "up"
    assert snap[peer].rtt_ms >= 0

    srv.close()
    mon.probe_all()
    assert mon.is_up(peer)  # one failure: not confirmed down yet
    mon.probe_all()
    assert not mon.is_up(peer)  # down_after=2 reached
    snap = {h.peer: h for h in mon.snapshot()}
    assert snap[peer].status == "down"
    assert snap[peer].consecutive_failures >= 2


def test_monitor_recovery():
    # A peer that starts dead and later comes up flips to "up".
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0)
    srv.start()
    port = srv.port
    srv.close()
    try:
        mon = PeerHealthMonitor([f"127.0.0.1:{port}"], timeout=0.3,
                                down_after=1)
        mon.probe_all()
        assert not mon.is_up(f"127.0.0.1:{port}")
        srv2 = NativeServer(eng, "127.0.0.1", port)
        srv2.start()  # raises on bind failure
        try:
            mon.probe_all()
            assert mon.is_up(f"127.0.0.1:{port}")
        finally:
            srv2.close()
    finally:
        eng.close()


def test_peers_verb_without_cluster_plane(server):
    _, srv = server
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        assert c.peers() == []  # native default: empty table


def test_peers_verb_serves_health_table(server):
    eng, srv = server
    # A second live node as the peer.
    peng = NativeEngine("mem")
    psrv = NativeServer(peng, "127.0.0.1", 0)
    psrv.start()
    cfg = Config()
    cfg.anti_entropy.enabled = True
    cfg.anti_entropy.peers = [f"127.0.0.1:{psrv.port}", "127.0.0.1:1"]
    cfg.anti_entropy.interval_seconds = 30  # loop mostly idle in this test
    node = ClusterNode(cfg, eng, srv)
    node.start()
    try:
        deadline = time.time() + 10
        rows = []
        while time.time() < deadline:
            with MerkleKVClient("127.0.0.1", srv.port) as c:
                rows = c.peers()
            if len(rows) == 2 and all(r["status"] != "unknown" for r in rows):
                break
            time.sleep(0.1)
        by_addr = {r["addr"]: r for r in rows}
        assert by_addr[f"127.0.0.1:{psrv.port}"]["status"] == "up"
        # port 1: nothing listens there; confirmed down after 2 probes.
        assert by_addr["127.0.0.1:1"]["status"] in ("down", "unknown")
    finally:
        node.stop()
        psrv.close()
        peng.close()


def test_sync_loop_skips_confirmed_down_peers(server):
    """The loop consults the failure detector and skips down peers (no
    connect timeout burned), while live peers still repair."""
    eng, srv = server
    peng = NativeEngine("mem")
    psrv = NativeServer(peng, "127.0.0.1", 0)
    psrv.start()
    peng.set(b"from-peer", b"repaired")

    down = {"127.0.0.1:1": False}  # detector verdict per peer

    def peer_up(p):
        return down.get(p, True)

    mgr = SyncManager(eng, device="cpu")
    mgr.start_loop(
        ["127.0.0.1:1", f"127.0.0.1:{psrv.port}"],
        interval_seconds=0.1,
        peer_up=peer_up,
    )
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            if eng.get(b"from-peer") == b"repaired":
                break
            time.sleep(0.05)
        assert eng.get(b"from-peer") == b"repaired"
        from merklekv_tpu.utils.tracing import get_metrics

        assert get_metrics().snapshot()["counters"].get(
            "anti_entropy.down_peer_skips", 0
        ) >= 1
    finally:
        mgr.stop()
        psrv.close()
        peng.close()


def test_metrics_verb_without_cluster_plane(server):
    _, srv = server
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        assert c.metrics() == {}  # native default: empty block


def test_metrics_verb_serves_control_plane_counters(server):
    eng, srv = server
    from merklekv_tpu.utils.tracing import get_metrics

    cfg = Config()
    node = ClusterNode(cfg, eng, srv)
    node.start()
    try:
        # Delta-based: the registry is process-global, so an absolute value
        # would break under reruns in one process.
        before = int(
            get_metrics().snapshot()["counters"].get("test_metrics.sentinel", 0)
        )
        get_metrics().inc("test_metrics.sentinel", 3)
        with MerkleKVClient("127.0.0.1", srv.port) as c:
            snap = c.metrics()
        assert snap.get("test_metrics.sentinel") == str(before + 3)
        # Counters are numeric text across the board.
        assert all(v.lstrip("-").isdigit() for v in snap.values()), snap
        # Span aggregates ride along (any span recorded by the control
        # plane shows as .count/.total_us plus bucket-derived percentiles
        # — may be absent if no span has run yet in this process; the
        # deprecated .total_ms is gone after its one-release window).
        for k in snap:
            if k.startswith("span."):
                assert k.endswith((".count", ".total_us",
                                   ".p50_us", ".p99_us")), k
    finally:
        node.stop()

"""Rebalance chaos through REAL processes (the CI rebalance chaos
smoke): a storage-backed 2-partition cluster plus one reserve, spawned
as real ``python -m merklekv_tpu`` nodes over a spawned broker, a live
2->3 ``REBALANCE SPLIT``, and a kill -9 (no shutdown path, no flush) of
EACH side mid-transfer:

- joiner killed mid-fetch -> the donor rolls the session back (epoch
  stays at 1, donor root bit-identical to pre-split — nothing lost,
  nothing dropped), and the SAME donor then completes a clean split
  against a respawned reserve;
- donor killed mid-fetch -> the joiner aborts back to reserve on its
  own, the respawned donor recovers its full keyspace from the WAL at
  the old epoch (root bit-identical), the offline blackbox analyzer
  exits 0 on the killed donor's flight spill, and a re-issued split
  commits — while a write storm against the OTHER partition rides
  through the whole drill with zero client-visible errors.

The transfer window is held open deterministically via the
MERKLEKV_REBALANCE_CHUNK_BYTES / MERKLEKV_REBALANCE_FETCH_PAUSE_S
chaos knobs (rebalance.py) so "mid-transfer" means mid-stream, not a
lucky race.
"""

import os
import socket
import subprocess
import sys
import threading
import time
import uuid

import pytest

from merklekv_tpu.client import MerkleKVClient, PartitionedClient
from merklekv_tpu.cluster.partmap import hash_of_key
from merklekv_tpu.testing.faults import PeerProcessKiller

pytestmark = pytest.mark.integration

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Hold the snapshot stream open ~2 s (tiny chunks + per-chunk pause) so
# the kill -9 lands mid-stream; shrink the joiner's donor-loss resolve
# budget so the drill doesn't wait out the production default.
CHAOS_ENV = {
    "MERKLEKV_REBALANCE_CHUNK_BYTES": "1024",
    "MERKLEKV_REBALANCE_FETCH_PAUSE_S": "0.05",
    "MERKLEKV_REBALANCE_RESOLVE_S": "8",
}


def _free_ports(n):
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class ProcCluster:
    """Broker + donor (p0) + sibling (p1) + one reserve, all real
    processes with durable storage, chaos knobs armed."""

    def __init__(self, tmp_path):
        self.tmp = tmp_path
        self.topic = f"rbproc-{uuid.uuid4().hex[:8]}"
        self.ports = _free_ports(3)
        self.addr = [f"127.0.0.1:{p}" for p in self.ports]
        self.spec = f"0={self.addr[0]};1={self.addr[1]}"
        self.procs = {}
        self.broker = self._spawn(["-m", "merklekv_tpu.broker",
                                   "--port", "0"])
        self.broker_port = self._port_from(self.broker)
        for i in range(3):
            self.spawn_node(i)

    def _spawn(self, args):
        env = dict(os.environ, PYTHONPATH=REPO,
                   MERKLEKV_JAX_PLATFORM="cpu", **CHAOS_ENV)
        return subprocess.Popen(
            [sys.executable, *args],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )

    def _port_from(self, proc):
        line = proc.stdout.readline()
        assert "listening on" in line, f"unexpected startup line: {line!r}"
        port = int(line.rsplit(":", 1)[1].split()[0])
        # Drain the rest so a chatty node never blocks on a full pipe.
        threading.Thread(
            target=lambda: [None for _ in proc.stdout], daemon=True
        ).start()
        return port

    def _wait_port(self, port, timeout=30):
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                socket.create_connection(
                    ("127.0.0.1", port), timeout=1
                ).close()
                return
            except OSError:
                time.sleep(0.05)
        raise TimeoutError(f"port {port} never came up")

    def node_toml(self, i):
        cluster = (
            f'[cluster]\npartitions = 2\npartition_id = {i}\n'
            f'partition_map = "{self.spec}"\n'
            if i < 2
            else ""
        )
        cfg = self.tmp / f"node-{i}.toml"
        cfg.write_text(
            f"""
host = "127.0.0.1"
port = {self.ports[i]}
engine = "mem"
storage_path = "{self.tmp}/n{i}"
{cluster}
[storage]
enabled = true
merkle_engine = "cpu"

[replication]
enabled = {"true" if i < 2 else "false"}
mqtt_broker = "127.0.0.1"
mqtt_port = {self.broker_port}
topic_prefix = "{self.topic}"

[anti_entropy]
engine = "cpu"
interval_seconds = 3600

[observability]
flight_spill_s = 0.5
"""
        )
        return cfg

    def spawn_node(self, i):
        proc = self._spawn(["-m", "merklekv_tpu", "--config",
                            str(self.node_toml(i))])
        self.procs[i] = proc
        self._wait_port(self._port_from(proc))
        return proc

    def kill9(self, i):
        killer = PeerProcessKiller(self.procs.pop(i))
        killer.kill_now()
        assert killer.killed

    def client(self, i, timeout=10):
        return MerkleKVClient("127.0.0.1", self.ports[i], timeout=timeout)

    def rebal_state(self, i):
        with self.client(i) as c:
            return c.rebalance("STATUS").split(" ")[1]

    def split(self, joiner=2):
        with self.client(0) as c:
            epoch = c.partition_map().epoch
            resp = c.rebalance(f"SPLIT 0 {epoch} {self.addr[joiner]}")
        assert resp.startswith("OK"), resp
        return resp

    def wait_state(self, i, want, timeout=60):
        deadline = time.time() + timeout
        state = None
        while time.time() < deadline:
            try:
                state = self.rebal_state(i)
            except OSError:
                state = None
            if state in want:
                return state
            time.sleep(0.02)
        raise TimeoutError(f"node {i} never reached {want} (last {state})")

    def close(self):
        for proc in list(self.procs.values()) + [self.broker]:
            proc.terminate()
        for proc in list(self.procs.values()) + [self.broker]:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


@pytest.fixture
def cluster(tmp_path):
    cl = ProcCluster(tmp_path)
    try:
        yield cl
    finally:
        cl.close()


def _seed(cl, n=3000):
    pc = PartitionedClient([cl.addr[0]], timeout=10).connect()
    for i in range(n):
        pc.set(f"rb:{i:06d}", f"v-{i}")
    pc.close()
    return {f"rb:{i:06d}": f"v-{i}" for i in range(n)}


def _root_of(cl, i, pid):
    with cl.client(i) as c:
        c.partition_id = pid  # pt=-addressed: MOVED if misrouted
        return c.hash()


def _dbsize_of(cl, i):
    with cl.client(i) as c:
        return c.dbsize()


def _readback_all(cl, kv):
    pc = PartitionedClient([cl.addr[1]], timeout=10).connect()
    try:
        missing = [k for k, v in kv.items() if pc.get(k) != v]
        assert not missing, f"{len(missing)} keys lost, e.g. {missing[:3]}"
    finally:
        pc.close()


def test_kill9_joiner_mid_transfer_then_clean_split(cluster):
    kv = _seed(cluster)
    root0 = _root_of(cluster, 0, 0)
    p0_before = _dbsize_of(cluster, 0)

    # Kill the joiner mid-stream: wait until it is actively fetching
    # (join_fetch), let a few chunks land, then SIGKILL.
    cluster.split(joiner=2)
    cluster.wait_state(2, {"join_fetch"}, timeout=30)
    time.sleep(0.3)
    cluster.kill9(2)

    # The donor declares the joiner dead and rolls the whole session
    # back: old epoch, bit-identical root, every key still served.
    cluster.wait_state(0, {"failed"}, timeout=60)
    with cluster.client(0) as c:
        m = c.partition_map()
    assert (m.epoch, m.count) == (1, 2)
    assert _root_of(cluster, 0, 0) == root0
    _readback_all(cluster, kv)

    # The SAME donor completes a clean split against a respawned
    # reserve — a failed rebalance must not poison the next one.
    cluster.spawn_node(2)
    cluster.split(joiner=2)
    cluster.wait_state(0, {"done"}, timeout=120)
    with cluster.client(0) as c:
        m = c.partition_map()
    assert (m.epoch, m.count) == (2, 3)
    moved = _dbsize_of(cluster, 2)
    assert moved > 0
    assert _dbsize_of(cluster, 0) + moved == p0_before
    _readback_all(cluster, kv)


def test_kill9_donor_mid_transfer_joiner_aborts_blackbox_parses(cluster):
    kv = _seed(cluster)
    root1 = _root_of(cluster, 1, 1)

    # A storm against partition 1 rides through the whole drill: the
    # donor's death mid-rebalance must not touch the other partition.
    p1_keys = [
        k for k in kv
        if hash_of_key(k.encode()) % 2 == 1
    ][:200]
    assert p1_keys
    errors = []
    stop = threading.Event()

    def storm():
        pc = PartitionedClient([cluster.addr[1]], timeout=10).connect()
        try:
            i = 0
            while not stop.is_set():
                pc.set(p1_keys[i % len(p1_keys)], kv[p1_keys[i % len(p1_keys)]])
                i += 1
                time.sleep(0.002)
        except BaseException as e:
            errors.append(e)
        finally:
            pc.close()

    t = threading.Thread(target=storm, daemon=True)
    t.start()
    try:
        cluster.split(joiner=2)
        cluster.wait_state(2, {"join_fetch"}, timeout=30)
        time.sleep(0.3)
        cluster.kill9(0)

        # The joiner loses its donor mid-stream, aborts on its own, and
        # returns to reserve duty holding nothing.
        cluster.wait_state(2, {"join_aborted"}, timeout=60)
        assert _dbsize_of(cluster, 2) == 0

        # The kill -9'd donor left a parseable black box behind.
        flight = os.path.join(
            str(cluster.tmp), "n0", f"node-{cluster.ports[0]}", "flight"
        )
        rc = subprocess.run(
            [sys.executable, "-m", "merklekv_tpu", "blackbox", flight],
            env=dict(os.environ, PYTHONPATH=REPO,
                     MERKLEKV_JAX_PLATFORM="cpu"),
            stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT,
        ).returncode
        assert rc == 0, f"blackbox analyzer failed on {flight}"

        # Respawn the donor: WAL recovery resurrects the FULL keyspace
        # at the old epoch (the commit point was never reached).
        cluster.spawn_node(0)
        with cluster.client(0) as c:
            m = c.partition_map()
        assert (m.epoch, m.count) == (1, 2)
        _readback_all(cluster, kv)

        # And the cluster is not poisoned: a re-issued split commits.
        cluster.wait_state(2, {"join_aborted", "idle"}, timeout=10)
        cluster.split(joiner=2)
        cluster.wait_state(0, {"done"}, timeout=120)
        with cluster.client(0) as c:
            m = c.partition_map()
        assert (m.epoch, m.count) == (2, 3)
        _readback_all(cluster, kv)
    finally:
        stop.set()
        t.join(timeout=10)

    assert not errors, f"storm saw errors: {errors[:3]!r}"
    assert _root_of(cluster, 1, 1) == root1  # p1 untouched by the drill

"""Protocol-level throughput benchmarks with the reference's enforced floors.

Mirrors the reference suite's thresholds
(/root/reference/tests/integration/test_benchmark.py):
  SET  > 1,000 ops/s (avg < 100 ms)      [:177-180]
  GET  > 2,000 ops/s (avg <  50 ms)      [:212-215]
  mixed > 800 ops/s (avg <  80 ms)       [:249-252]
  >= 95% of 50 concurrent connections OK [:316-317]
  10-client throughput >= 0.5x 1-client  [:341-343]

The native server clears these floors by orders of magnitude; the asserts
keep the SAME numbers as the reference so regressions trip the same wire.
"""

import os
import threading
import time

import pytest

from merklekv_tpu.client import MerkleKVClient
from merklekv_tpu.native_bindings import NativeEngine, NativeServer

pytestmark = pytest.mark.benchmark


@pytest.fixture
def server():
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0)
    srv.start()
    yield srv
    srv.close()
    eng.close()


def _hammer(port, n_clients, ops_per_client, op):
    """Run op(client, client_id, i) from n_clients threads; return
    (total_ops, wall_seconds, latencies, errors)."""
    lat: list[float] = []
    errors: list[Exception] = []
    lock = threading.Lock()

    def worker(cid):
        try:
            with MerkleKVClient("127.0.0.1", port) as c:
                local = []
                for i in range(ops_per_client):
                    t0 = time.perf_counter()
                    op(c, cid, i)
                    local.append(time.perf_counter() - t0)
                with lock:
                    lat.extend(local)
        except Exception as e:  # pragma: no cover
            with lock:
                errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    return n_clients * ops_per_client, wall, lat, errors


def test_set_throughput_floor(server):
    n, wall, lat, errors = _hammer(
        server.port, 5, 400, lambda c, cid, i: c.set(f"s{cid}:{i}", f"v{i}")
    )
    assert not errors
    ops_s = n / wall
    avg_ms = 1000 * sum(lat) / len(lat)
    print(f"\nSET: {ops_s:,.0f} ops/s, avg {avg_ms:.3f} ms")
    assert ops_s > 1000  # reference floor
    assert avg_ms < 100


def test_get_throughput_floor(server):
    with MerkleKVClient("127.0.0.1", server.port) as c:
        c.mset({f"g{i}": f"v{i}" for i in range(1000)})
    n, wall, lat, errors = _hammer(
        server.port, 5, 400, lambda c, cid, i: c.get(f"g{i % 1000}")
    )
    assert not errors
    ops_s = n / wall
    avg_ms = 1000 * sum(lat) / len(lat)
    print(f"\nGET: {ops_s:,.0f} ops/s, avg {avg_ms:.3f} ms")
    assert ops_s > 2000  # reference floor
    assert avg_ms < 50


def test_mixed_workload_floor(server):
    def op(c, cid, i):
        if i % 3 == 0:
            c.set(f"m{cid}:{i}", f"v{i}")
        elif i % 3 == 1:
            c.get(f"m{cid}:{i - 1}")
        else:
            c.delete(f"m{cid}:{i - 2}")

    n, wall, lat, errors = _hammer(server.port, 10, 150, op)
    assert not errors
    ops_s = n / wall
    avg_ms = 1000 * sum(lat) / len(lat)
    print(f"\nmixed: {ops_s:,.0f} ops/s, avg {avg_ms:.3f} ms")
    assert ops_s > 800  # reference floor
    assert avg_ms < 80


def test_concurrent_connections(server):
    ok = []
    lock = threading.Lock()

    def connect_and_op(i):
        try:
            with MerkleKVClient("127.0.0.1", server.port, timeout=30) as c:
                c.set(f"conn{i}", "x")
                assert c.get(f"conn{i}") == "x"
            with lock:
                ok.append(i)
        except Exception:
            pass

    threads = [threading.Thread(target=connect_and_op, args=(i,)) for i in range(50)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert time.perf_counter() - t0 < 30
    assert len(ok) >= 48  # >= 95% of 50


def test_scalability_sanity(server):
    """10-client aggregate throughput >= 0.5x single-client throughput."""

    def run(n_clients):
        n, wall, _, errors = _hammer(
            server.port, n_clients, 300,
            lambda c, cid, i: c.set(f"sc{cid}:{i}", "v"),
        )
        assert not errors
        return n / wall

    single = run(1)
    ten = run(10)
    print(f"\n1 client: {single:,.0f} ops/s; 10 clients: {ten:,.0f} ops/s")
    assert ten >= 0.5 * single


def test_pipeline_throughput(server):
    """Pipelined batches: the native server drains whole request buffers."""
    with MerkleKVClient("127.0.0.1", server.port) as c:
        cmds = [f"SET p{i} v{i}" for i in range(5000)]
        t0 = time.perf_counter()
        out = c.pipeline(cmds)
        wall = time.perf_counter() - t0
        assert all(r == "OK" for r in out)
        ops_s = len(cmds) / wall
        print(f"\npipelined SET: {ops_s:,.0f} ops/s")
        assert ops_s > 10_000  # reference's claimed sustained throughput


def test_kernel_bench_tool_smoke(monkeypatch, capfd):
    """tools/kernel_bench.py runs end-to-end off-TPU and emits valid JSON
    rows for the scan baselines (the Pallas rows are chip-only)."""
    import json
    import runpy

    # Lazy backend check: collection must not import (let alone claim) the
    # jax backend for a module whose other tests are jax-free.
    import jax

    if jax.default_backend() == "tpu":
        pytest.skip(
            "smoke run is the off-TPU path; on-chip kernels are covered by "
            "tests/test_sha256_pallas.py, and the full 4M-leaf bench does "
            "not belong inside the suite"
        )

    monkeypatch.setenv("MKV_KB_REPS", "2")
    runpy.run_path(
        os.path.join(os.path.dirname(os.path.dirname(__file__)),
                     "tools", "kernel_bench.py"),
        run_name="__main__",
    )
    out = capfd.readouterr().out
    rows = [json.loads(line) for line in out.strip().splitlines()]
    kernels = {r["kernel"] for r in rows}
    assert {"sha256_blocks_scan", "sha256_node_pairs_scan",
            "build_levels_dispatch"} <= kernels
    assert all(r["ms"] > 0 for r in rows)


def test_bench_failure_still_emits_json_record(monkeypatch, capsys):
    """The driver contract hardening (VERDICT top-next): when the data
    plane dies — no TPU, no working jax, whatever — bench.main() must
    still leave ONE parsable JSON record on stdout and return normally
    (BENCH_r05 regressed to rc=1 with parsed=null)."""
    import json

    import bench

    monkeypatch.setattr(bench, "_resolve_backend", lambda: "cpu")

    def boom(*a, **kw):
        raise RuntimeError("backend exploded mid-bench")

    monkeypatch.setattr(bench, "bench_cpu", boom)
    bench.main()  # must not raise
    out = capsys.readouterr().out.strip().splitlines()
    rec = json.loads(out[-1])
    assert rec["metric"] == "merkle_rebuild_diff_keys_per_s"
    assert rec["value"] is None
    assert "backend exploded" in rec["error"]
    assert rec["backend"] == "cpu"


def test_backend_probe_is_bounded(monkeypatch):
    """probe_default_backend resolves in a subprocess and respects its
    deadline — a hung backend init can no longer wedge the bench."""
    from merklekv_tpu.utils.jaxenv import probe_default_backend

    # A CPU-pinned environment short-circuits without a subprocess.
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert probe_default_backend(timeout=0.001) == "cpu"
    # Unpinned, an absurdly short deadline forces the timeout path
    # deterministically (the child is spawned and killed) — the exact
    # degradation a hung tunneled-TPU init produces.
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.delenv("MERKLEKV_JAX_PLATFORM", raising=False)
    assert probe_default_backend(timeout=0.001) is None


def test_bench_main_rc0_under_poisoned_jax_platforms():
    """Regression for the BENCH_r05 failure shape: a real `python bench.py`
    subprocess with JAX_PLATFORMS poisoned to an unusable platform must
    STILL exit 0 with one parsable JSON record on stdout — the raw
    `jax.default_backend()` crash path must stay routed through the
    bounded-probe/fallback contract."""
    import json
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "bogusplatform"  # pinned AND unusable
    env.pop("MERKLEKV_JAX_PLATFORM", None)
    env["MKV_BENCH_PROBE_TIMEOUT"] = "15"
    out = subprocess.run(
        [sys.executable, "bench.py"],
        cwd=root,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert out.returncode == 0, (out.returncode, out.stderr[-2000:])
    records = [
        ln for ln in out.stdout.splitlines() if ln.strip().startswith("{")
    ]
    assert records, f"no JSON on stdout: {out.stdout!r}"
    rec = json.loads(records[-1])
    assert rec["metric"] == "merkle_rebuild_diff_keys_per_s"
    # A poisoned platform cannot produce a number; the record must carry
    # the failure instead of the process carrying a traceback + rc 1.
    assert rec["value"] is None
    assert rec.get("error")

"""Live partition rebalancing (ISSUE 16): epoch-bumped online resharding.

Covers the split-tree map plane (durable map file, mapspec v2, wire v2),
the REBALANCE verb's wire surface (including truncation/byte-flip fuzz of
REBALSTATUS and the epoch-bearing split PARTMAP), the donor snapshot-pin
heartbeat, and the chaos drills: a clean live split under client write
load with ZERO visible errors and bit-identical verified roots; joiner
death mid-transfer rolling the donor back with uninterrupted service;
donor-session death rolling the joiner back to reserve; a lost COMMIT
healing through the joiner's self-commit resolve loop; sibling fence TTL
expiry restoring write availability; and the durable map-file overlay
resurrecting both a committed donor and a committed joiner at epoch E+1
after a restart.
"""

import os
import socket
import threading
import time
import uuid

import pytest

from merklekv_tpu.client import (
    MerkleKVClient,
    MerkleKVError,
    PartitionedClient,
    ProtocolError,
    ServerBusyError,
)
from merklekv_tpu.cluster import rebalance as rb_mod
from merklekv_tpu.cluster.node import ClusterNode
from merklekv_tpu.cluster.partmap import (
    PartitionMap,
    PartitionMapError,
    format_map_spec,
    key_in_range,
    load_map_file,
    parse_map_spec,
    partition_of,
    save_map_file,
)
from merklekv_tpu.cluster.transport import TcpBroker
from merklekv_tpu.config import Config
from merklekv_tpu.native_bindings import NativeEngine, NativeServer
from merklekv_tpu.obs.flightrec import get_recorder
from merklekv_tpu.storage import DurableStore
from merklekv_tpu.storage import snapshot as snapmod


def wait_for(fn, timeout=15.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def free_ports(n: int) -> list[int]:
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


# ------------------------------------------------------------ map plane


def test_split_preserves_routing_and_moving_range():
    m = parse_map_spec("0=a:1;1=b:2", 2, epoch=1)
    s = m.split(0, ["c:3"])
    assert s.epoch == 2 and s.count == 3 and s.is_split
    assert s.replicas[2] == ["c:3"]
    # The moving range of the ORIGINAL map is exactly the new pid's cell.
    assert m.moving_range(0) == (s.hash_base, *s.assignment(2))
    # Routing is a partition: every key lands on exactly one owner, and
    # keys that stay route identically before and after.
    for i in range(300):
        k = f"route:{i}"
        owners = [
            p
            for p in range(s.count)
            if key_in_range(k, s.hash_base, *s.assignment(p))
        ]
        assert len(owners) == 1, f"{k} owned by {owners}"
        if owners[0] != 2:
            assert owners[0] == partition_of(k, 2)


def test_map_file_roundtrip_and_malformations(tmp_path):
    m = parse_map_spec("0=a:1;1=b:2", 2, epoch=1).split(0, ["c:3"])
    save_map_file(str(tmp_path), m, 2)
    loaded = load_map_file(str(tmp_path))
    assert loaded is not None
    pmap, pid = loaded
    assert pid == 2 and pmap == m and pmap.epoch == 2
    # Missing file is a clean None (fresh node), never an exception.
    assert load_map_file(str(tmp_path / "nowhere")) is None
    # Any malformation raises: ownership is never guessed from a torn
    # or doctored file.
    path = tmp_path / "partmap.spec"
    good = path.read_text()
    bad = [
        "",  # empty
        "BOGUSMAGIC\n" + good.split("\n", 1)[1],  # wrong magic
        good.replace("epoch 2", "epoch x"),  # non-numeric epoch
        good.replace("pid 2", "pid 9"),  # pid out of range
        "\n".join(good.split("\n")[:3]) + "\n",  # truncated
        good.replace("spec ", "spec !"),  # garbled mapspec
    ]
    for blob in bad:
        path.write_text(blob)
        with pytest.raises(PartitionMapError):
            load_map_file(str(tmp_path))
    # A half-written temp file never shadows the real one.
    path.write_text(good)
    (tmp_path / "partmap.spec.tmp").write_text("garbage")
    assert load_map_file(str(tmp_path))[1] == 2


def test_mapspec_v2_roundtrip_single_token():
    m = parse_map_spec("0=a:1,b:2;1=c:3", 2, epoch=3).split(1, ["d:4"])
    spec = format_map_spec(m)
    assert " " not in spec  # must ride the wire as ONE token
    again = parse_map_spec(spec, m.count, m.epoch)
    assert again == m
    # Wire v2 roundtrip (4-field epoch-bearing header).
    parsed = PartitionMap.from_wire(
        m.wire().split("\r\n")[0], m.wire().split("\r\n")[1:-2]
    )
    assert parsed == m


# ----------------------------------------------------- wire verb surface


@pytest.fixture
def bare_partitioned_node():
    ports = free_ports(2)
    spec = f"0=127.0.0.1:{ports[0]};1=127.0.0.1:{ports[1]}"
    cfg = Config()
    cfg.host = "127.0.0.1"
    cfg.port = ports[0]
    cfg.cluster.partitions = 2
    cfg.cluster.partition_id = 0
    cfg.cluster.partition_map = spec
    cfg.anti_entropy.engine = "cpu"
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", ports[0])
    srv.start()
    node = ClusterNode(cfg, eng, srv)
    node.start()
    yield node, srv
    node.stop()
    srv.close()
    eng.close()


def test_rebalance_wire_refusals(bare_partitioned_node):
    node, srv = bare_partitioned_node
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        for sub, why in [
            ("", "subcommand"),
            ("NONSENSE", "unknown"),
            ("SPLIT", "requires"),
            ("SPLIT 1 1 h:1", "not 1"),  # this node serves 0
            ("SPLIT 0 9 h:1", "stale epoch"),
            ("SPLIT 0 1 h:1", "storage"),  # no durable storage
            ("SPLIT x y z", "invalid literal"),
            ("JOIN 2 3 2 h:1 base=2;0@0.1.0=a:1;1@1.0.0=b:2;2@0.1.1=c:3",
             "reserve"),  # partitioned nodes refuse conscription
            ("FENCE 9 2 0 1 1 1000", "does not extend"),
            ("COMMIT 2 3", "requires"),
        ]:
            with pytest.raises(ProtocolError, match=why):
                c.rebalance(sub)
        # STATUS always answers (idle node), never an error.
        assert c.rebalance("STATUS").startswith("REBALSTATUS idle 1 ")
        # COMMIT of an epoch we already have is idempotent-OK.
        spec = format_map_spec(node._partmap)
        assert c.rebalance(f"COMMIT 1 2 {spec}") == "OK committed"


# -------------------------------------------------- wire fuzz (satellite)


class _CannedServer:
    """One-shot server: accept, read one line, answer canned bytes,
    close — the hostile-peer rig for wire fuzzing."""

    def __init__(self, payload: bytes) -> None:
        self._payload = payload
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(4)
        self.port = self._sock.getsockname()[1]
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        try:
            conn, _ = self._sock.accept()
            conn.settimeout(5)
            try:
                conn.recv(4096)
                conn.sendall(self._payload)
            finally:
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                conn.close()
        except OSError:
            pass

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=5)


class _FakeNode:
    """Just enough node for RebalanceManager's client-side paths."""

    _partmap = None
    _partition_id = None


def _poll_status_from_canned(payload: bytes):
    srv = _CannedServer(payload)
    mgr = rb_mod.RebalanceManager(_FakeNode())
    try:
        return mgr._poll_status(f"127.0.0.1:{srv.port}")
    finally:
        srv.close()


def test_rebalstatus_fuzz_truncation_every_offset():
    """A REBALSTATUS reply cut at EVERY byte offset either parses whole
    or raises a clean typed error — never a partial status, never a hang,
    never a non-client exception (a garbled status steering a donor's
    flip decision would be a zero-loss violation)."""
    good = b"REBALSTATUS join_live 2 " + b"ab" * 32 + b"\r\n"
    for cut in range(len(good) + 1):
        try:
            state, epoch, root = _poll_status_from_canned(good[:cut])
        except (MerkleKVError, OSError):
            continue
        assert cut >= len(good) - 2, f"partial status accepted at {cut}"
        assert (state, epoch) == ("join_live", 2)


def test_rebalstatus_fuzz_seeded_byte_flips():
    import random

    good = b"REBALSTATUS transfer 3 -\r\n"
    rng = random.Random(1601)
    for _ in range(48):
        pos = rng.randrange(len(good))
        payload = (
            good[:pos]
            + bytes([good[pos] ^ (1 << rng.randrange(8))])
            + good[pos + 1:]
        )
        try:
            state, epoch, _ = _poll_status_from_canned(payload)
        except (MerkleKVError, OSError):
            continue
        # Whatever survived the flip is structurally whole.
        assert isinstance(epoch, int) and state


def _fetch_map_from_canned(payload: bytes):
    srv = _CannedServer(payload)
    try:
        with MerkleKVClient("127.0.0.1", srv.port, timeout=2.0) as c:
            return c.partition_map()
    finally:
        srv.close()


_SPLIT_PARTMAP_WIRE = (
    "PARTMAP 2 3 2\r\n"
    "0 0.1.0 127.0.0.1:7001\r\n"
    "1 1.0.0 127.0.0.1:7003\r\n"
    "2 0.1.1 127.0.0.1:7005\r\n"
    "END\r\n"
).encode()


def test_split_partmap_fuzz_truncation_every_offset():
    """The epoch-bearing SPLIT map reply (wire v2, 4-field header) cut at
    every offset: full parse or clean typed error, never a partial map —
    a client routing on half a split table would wrong-node silently."""
    full_len = len(_SPLIT_PARTMAP_WIRE)
    for cut in range(full_len + 1):
        try:
            m = _fetch_map_from_canned(_SPLIT_PARTMAP_WIRE[:cut])
        except (MerkleKVError, PartitionMapError):
            continue
        assert cut >= full_len - 2, f"partial split map accepted at {cut}"
        assert m.is_split and m.epoch == 2 and m.count == 3
        assert m.hash_base == 2
        assert m.assignment(2) == (0, 1, 1)


def test_split_partmap_fuzz_seeded_byte_flips():
    import random

    rng = random.Random(1602)
    for _ in range(64):
        pos = rng.randrange(len(_SPLIT_PARTMAP_WIRE))
        payload = (
            _SPLIT_PARTMAP_WIRE[:pos]
            + bytes([_SPLIT_PARTMAP_WIRE[pos] ^ (1 << rng.randrange(8))])
            + _SPLIT_PARTMAP_WIRE[pos + 1:]
        )
        try:
            m = _fetch_map_from_canned(payload)
        except (MerkleKVError, PartitionMapError):
            continue
        m.validate()  # whatever came back is a complete, coherent map
        # A flipped map is still a partition of the keyspace: one owner
        # per key (the invariant routing correctness rides on).
        for i in range(40):
            k = f"fz:{i}"
            owners = [
                p
                for p in range(m.count)
                if key_in_range(k, m.hash_base, *m.assignment(p))
            ]
            assert len(owners) == 1


# ------------------------------------------- donor pin heartbeat (the fix)


def test_rebalance_heartbeat_outlives_pin_ttl(tmp_path, monkeypatch):
    """The satellite fix: a throttled transfer pausing longer than the
    donor's pin TTL between chunks must NOT lose its artifact — the
    rebalance session heartbeat (refresh_pin with no seq) re-stamps every
    live pin, so retention keeps the pinned snapshot; silence past the
    TTL (a dead session) still releases it."""
    cfg = Config()
    cfg.storage.enabled = True
    cfg.storage.merkle_engine = "cpu"
    cfg.storage.snapshots_retained = 1
    eng = NativeEngine("mem")
    store = DurableStore(eng, cfg.storage, str(tmp_path))
    store.recover()
    try:
        monkeypatch.setattr(DurableStore, "_PIN_TTL_S", 0.3)
        eng.set(b"a", b"1")
        store.snapshot_now()
        meta = store.donor_meta()  # pins the artifact
        assert isinstance(meta, tuple)
        seq = meta[0]
        # Age the pin past the TTL repeatedly, heartbeating each time —
        # then force retention churn with newer snapshots.
        for i in range(3):
            time.sleep(0.15)
            store.refresh_pin()  # the session heartbeat
        eng.set(b"b", b"2")
        store.snapshot_now()
        eng.set(b"c", b"3")
        store.snapshot_now()  # retention runs; pinned artifact must survive
        assert store.read_snapshot_range(seq, 0, 64), (
            "heartbeated pin lost its artifact"
        )
        # A dead session (no heartbeat past the TTL) releases the pin.
        time.sleep(0.4)
        eng.set(b"d", b"4")
        store.snapshot_now()
        with pytest.raises(OSError):
            store.read_snapshot_range(seq, 0, 64)
    finally:
        store.stop()
        eng.close()


# ------------------------------------------------- in-process split rigs


class RebalCluster:
    """2 partitions x 1 replica + reserves, storage-backed, replicating
    over one shared broker — the in-process live-split rig."""

    def __init__(self, tmp_path, reserves: int = 1) -> None:
        self.tmp = tmp_path
        self.broker = TcpBroker()
        self.topic = f"rb-{uuid.uuid4().hex[:8]}"
        self.ports = free_ports(2 + reserves)
        self.addr = [f"127.0.0.1:{p}" for p in self.ports]
        self.spec = f"0={self.addr[0]};1={self.addr[1]}"
        self.engines: dict[int, NativeEngine] = {}
        self.stores: dict[int, DurableStore] = {}
        self.servers: dict[int, NativeServer] = {}
        self.nodes: dict[int, ClusterNode] = {}
        for i in range(2 + reserves):
            self.start_node(i)

    def cfg_for(self, i: int) -> Config:
        cfg = Config()
        cfg.host = "127.0.0.1"
        cfg.port = self.ports[i]
        cfg.storage.enabled = True
        cfg.storage.merkle_engine = "cpu"
        cfg.anti_entropy.engine = "cpu"
        cfg.anti_entropy.interval_seconds = 3600.0
        cfg.replication.mqtt_broker = self.broker.host
        cfg.replication.mqtt_port = self.broker.port
        cfg.replication.topic_prefix = self.topic
        if i < 2:  # partition members; the rest are reserves
            cfg.cluster.partitions = 2
            cfg.cluster.partition_id = i
            cfg.cluster.partition_map = self.spec
            cfg.replication.enabled = True
        return cfg

    def start_node(self, i: int) -> ClusterNode:
        eng = self.engines.get(i)
        if eng is None:
            eng = NativeEngine("mem")
            self.engines[i] = eng
        d = os.path.join(str(self.tmp), f"n{i}")
        os.makedirs(d, exist_ok=True)
        store = DurableStore(eng, self.cfg_for(i).storage, d)
        store.recover()
        self.stores[i] = store
        srv = NativeServer(eng, "127.0.0.1", self.ports[i])
        srv.start()
        self.servers[i] = srv
        node = ClusterNode(self.cfg_for(i), eng, srv, storage=store)
        node.start()
        self.nodes[i] = node
        return node

    def kill(self, i: int) -> None:
        """Abrupt death: stop serving first, then tear down in the
        __main__ order (node, storage, server) — storage's final drain
        reads through live server handles."""
        srv = self.servers.pop(i)
        srv.stop()
        node = self.nodes.pop(i)
        try:
            node.stop()
        except Exception:
            pass
        store = self.stores.pop(i)
        try:
            store.stop()
        except Exception:
            pass
        srv.close()

    def client(self, i: int, timeout=5.0) -> MerkleKVClient:
        host, _, port = self.addr[i].rpartition(":")
        return MerkleKVClient(host, int(port), timeout=timeout)

    def split(self, donor: int = 0, joiner: int = 2) -> str:
        with self.client(donor, timeout=10) as c:
            epoch = c.partition_map().epoch
            return c.rebalance(f"SPLIT 0 {epoch} {self.addr[joiner]}")

    def donor_state(self, i: int = 0) -> str:
        with self.client(i) as c:
            return c.rebalance("STATUS").split(" ")[1]

    def close(self) -> None:
        # __main__'s shutdown order per node: node, storage, server,
        # engine — storage's final drain reads through live handles.
        for i in list(self.nodes):
            try:
                self.nodes[i].stop()
            except Exception:
                pass
        for store in self.stores.values():
            try:
                store.stop()
            except Exception:
                pass
        for srv in self.servers.values():
            srv.close()
        for eng in self.engines.values():
            eng.close()
        self.broker.close()


def _seed(pc, n=200, tag="k"):
    kv = {}
    for i in range(n):
        k = f"{tag}:{i:05d}"
        kv[k] = f"v{i}"
        pc.set(k, kv[k])
    return kv


# ------------------------------------------------------ the clean split


def test_live_split_zero_errors_and_verified_handoff(tmp_path):
    """The tentpole headline, in process: a live 2->3 split under client
    write load — zero client-visible errors, epoch flip to E+1, donor and
    joiner keyspaces disjoint with their union exactly the pre-split set
    plus the storm's writes, the joiner's engine root bit-identical to a
    CPU-recomputed reference over the moving range, stale clients healing
    through MOVED, and the durable map file present on both sides."""
    rec = get_recorder()
    rec.clear()
    cluster = RebalCluster(tmp_path)
    storm_errors: list = []
    try:
        pc = PartitionedClient([cluster.addr[0]], timeout=5).connect()
        kv = _seed(pc)
        stop = threading.Event()
        wrote: dict[str, str] = {}

        def storm():
            i = 0
            try:
                while not stop.is_set():
                    k = f"live:{i:05d}"
                    pc2.set(k, f"L{i}")
                    wrote[k] = f"L{i}"
                    i += 1
                    time.sleep(0.002)
            except BaseException as e:
                storm_errors.append(e)

        pc2 = PartitionedClient([cluster.addr[0]], timeout=5).connect()
        t = threading.Thread(target=storm, daemon=True)
        t.start()
        time.sleep(0.05)
        assert cluster.split().startswith("OK rebalance started 2 2")
        assert wait_for(
            lambda: cluster.donor_state() in ("done", "failed"), timeout=60
        )
        assert cluster.donor_state() == "done"
        time.sleep(0.3)
        stop.set()
        t.join(timeout=10)
        assert not storm_errors, f"client-visible error: {storm_errors[0]!r}"
        assert wrote, "storm never wrote"

        allkv = dict(kv)
        allkv.update(wrote)
        # Epoch flipped, split map served.
        with cluster.client(0) as c:
            m = c.partition_map()
        assert m.epoch == 2 and m.count == 3 and m.is_split

        # No key lost, none double-owned: donor + joiner partition the
        # old partition-0 keyspace exactly.
        donor_keys = {k for k, _ in cluster.engines[0].snapshot()}
        joiner_keys = {k for k, _ in cluster.engines[2].snapshot()}
        assert not donor_keys & joiner_keys, "double-owned keys"
        expect_p0 = {
            k.encode() for k in allkv if partition_of(k, 2) == 0
        }
        assert donor_keys | joiner_keys == expect_p0
        assert joiner_keys, "nothing actually moved"

        # Bit-identical root: the joiner's whole engine vs an independent
        # CPU recomputation over exactly the moving-range subset.
        ref = snapmod.compute_root_hex(
            sorted(
                (k.encode(), v.encode())
                for k, v in allkv.items()
                if key_in_range(k, m.hash_base, *m.assignment(2))
            ),
            engine="cpu",
        )
        joiner_root = snapmod.compute_root_hex(
            cluster.engines[2].snapshot(), engine="cpu"
        )
        assert joiner_root == ref, "moved range not bit-identical"

        # Every key readable through the (now-stale) seeded client: MOVED
        # -> refresh -> re-route, no errors.
        for k in list(allkv)[::9]:
            assert pc.get(k) == allkv[k]

        # Durable commit point on both sides.
        assert load_map_file(os.path.join(str(tmp_path), "n0"))[0].epoch == 2
        jm, jpid = load_map_file(os.path.join(str(tmp_path), "n2"))
        assert jm.epoch == 2 and jpid == 2

        # Observability: phases in the flight ring, terminal gauge state.
        kinds = {e.kind for e in rec.last(0)}
        assert "rebalance_start" in kinds
        assert "rebalance_verified" in kinds
        assert "rebalance_commit" in kinds
        assert "rebalance_done" in kinds
        assert cluster.nodes[0]._rebalance_state_code() == 7  # done
        assert cluster.nodes[2]._rebalance_state_code() == 13  # committed
        m0 = dict(
            ln.split(":", 1)
            for ln in cluster.nodes[0]._metrics_wire().splitlines()
            if ":" in ln
        )
        assert m0["partition.epoch"] == "2"
        assert m0["rebalance.state"] == "7"
        pay = cluster.nodes[2]._health_payload()
        assert pay["partition"] == 2 and pay["partition_epoch"] == 2
        pc.close()
        pc2.close()
    finally:
        cluster.close()


def test_joiner_death_mid_transfer_donor_rolls_back(tmp_path, monkeypatch):
    """Kill the joiner while the transfer is provably in flight: the
    donor aborts, stays at epoch E serving every key (reads AND writes,
    fence never armed), and a later split against a fresh reserve
    succeeds — one wasted transfer, zero lost keys."""
    monkeypatch.setattr(rb_mod, "_POLL_FAILURE_BUDGET", 4)
    cluster = RebalCluster(tmp_path, reserves=2)
    try:
        pc = PartitionedClient([cluster.addr[0]], timeout=5).connect()
        kv = _seed(pc)
        # Hold the joiner mid-install so the kill window is deterministic.
        jmgr = cluster.nodes[2]._rebalance_manager()
        held = threading.Event()

        def holding_install(snap, moving):
            held.set()
            jmgr._stop_evt.wait(timeout=30)
            raise RuntimeError("simulated joiner crash")

        monkeypatch.setattr(jmgr, "_install_filtered", holding_install)
        assert cluster.split().startswith("OK")
        assert held.wait(timeout=30), "joiner never reached the transfer"
        cluster.kill(2)  # the abrupt death, mid-transfer
        assert wait_for(
            lambda: cluster.donor_state() == "failed", timeout=30
        )
        # Rollback: epoch unchanged, no map file, every key served.
        with cluster.client(0) as c:
            assert c.partition_map().epoch == 1
        assert load_map_file(os.path.join(str(tmp_path), "n0")) is None
        for k in list(kv)[::9]:
            assert pc.get(k) == kv[k]
        p0 = next(k for k in kv if partition_of(k, 2) == 0)
        assert pc.set(p0, "post-abort")  # writes open: fence never stuck
        # The donor's forward hook is disarmed (no leak into dead topics).
        assert cluster.nodes[0].replicator._fwd_topic is None
        # The SAME donor can split again against the second reserve.
        with cluster.client(0, timeout=10) as c:
            assert c.rebalance(
                f"SPLIT 0 1 {cluster.addr[3]}"
            ).startswith("OK")
        assert wait_for(
            lambda: cluster.donor_state() == "done", timeout=60
        )
        with cluster.client(0) as c:
            assert c.partition_map().epoch == 2
        pc.close()
    finally:
        cluster.close()


def test_donor_session_death_joiner_returns_to_reserve(
    tmp_path, monkeypatch
):
    """The donor's session dies silently mid-transfer (the crash shape:
    no ABORT ever sent) and comes back idle at epoch E: the joiner's
    resolve loop reads that verdict and wipes itself back to an empty,
    serving reserve — no half-joined zombie, no double ownership."""
    cluster = RebalCluster(tmp_path)
    try:
        pc = PartitionedClient([cluster.addr[0]], timeout=5).connect()
        kv = _seed(pc)
        dmgr = cluster.nodes[0]._rebalance_manager()
        orig_wait = dmgr._wait_joiner_live

        def die_after_live(joiner):
            orig_wait(joiner)  # joiner IS conscripted and live
            raise RuntimeError("simulated donor crash")

        def silent_crash(**kw):
            # A kill -9 sends no ABORT and clears nothing remotely; the
            # restarted donor simply reports idle at epoch E.
            cluster.nodes[0].replicator.clear_range_forward()
            dmgr._set_state("idle")
            with dmgr._mu:
                dmgr._pending = None

        monkeypatch.setattr(dmgr, "_wait_joiner_live", die_after_live)
        monkeypatch.setattr(
            dmgr, "_abort_split", lambda **kw: silent_crash()
        )
        assert cluster.split().startswith("OK")
        # Joiner reaches live, then resolves the dead session: rollback.
        assert wait_for(
            lambda: cluster.nodes[2]._rebalance_manager().state
            == "join_aborted",
            timeout=30,
        ), cluster.nodes[2]._rebalance_manager().state
        jnode = cluster.nodes[2]
        assert jnode._partmap is None and jnode._partition_id is None
        assert cluster.engines[2].dbsize() == 0  # wiped back to empty
        with cluster.client(2) as c:
            assert c.set("any:key", "reserve-serves")  # guard cleared
        # The donor still owns everything at epoch E.
        with cluster.client(0) as c:
            assert c.partition_map().epoch == 1
        for k in list(kv)[::19]:
            assert pc.get(k) == kv[k]
        pc.close()
    finally:
        cluster.close()


def test_lost_commit_heals_through_joiner_self_commit(
    tmp_path, monkeypatch
):
    """The donor commits (map persisted, epoch flipped) but its COMMIT
    broadcast to the joiner is lost: the joiner's resolve loop sees the
    donor's terminal state at E+1 and self-commits — serving its new
    partition without ever hearing COMMIT."""
    cluster = RebalCluster(tmp_path)
    try:
        pc = PartitionedClient([cluster.addr[0]], timeout=5).connect()
        kv = _seed(pc)
        dmgr = cluster.nodes[0]._rebalance_manager()
        orig_rpc = dmgr._rpc

        def dropping_rpc(addr, subcommand, ignore_errors=False):
            if subcommand.startswith("COMMIT") and addr == cluster.addr[2]:
                return None  # the lost broadcast
            return orig_rpc(addr, subcommand, ignore_errors=ignore_errors)

        monkeypatch.setattr(dmgr, "_rpc", dropping_rpc)
        assert cluster.split().startswith("OK")
        assert wait_for(
            lambda: cluster.donor_state() == "done", timeout=60
        )
        # The joiner self-commits off the donor's terminal state.
        assert wait_for(
            lambda: cluster.nodes[2]._rebalance_manager().state
            == "join_committed",
            timeout=30,
        ), cluster.nodes[2]._rebalance_manager().state
        jnode = cluster.nodes[2]
        assert jnode._partmap.epoch == 2 and jnode._partition_id == 2
        # And it serves: moved keys are readable THROUGH the new map.
        moved = [
            k
            for k in kv
            if key_in_range(
                k, jnode._partmap.hash_base, *jnode._partmap.assignment(2)
            )
        ]
        assert moved
        for k in moved[::7]:
            assert pc.get(k) == kv[k]
        pc.close()
    finally:
        cluster.close()


def test_restart_both_sides_resurrect_committed_epoch(tmp_path):
    """Kill donor AND joiner after a committed split; restart both from
    their storage directories with their ORIGINAL boot configs (donor:
    old 2-way map at epoch 1; joiner: unpartitioned reserve). Both must
    come back at epoch 2 owning their narrowed/new cells — the durable
    map file IS the epoch, the boot config is just the seed."""
    cluster = RebalCluster(tmp_path)
    try:
        pc = PartitionedClient([cluster.addr[0]], timeout=5).connect()
        kv = _seed(pc)
        assert cluster.split().startswith("OK")
        assert wait_for(
            lambda: cluster.donor_state() == "done", timeout=60
        )
        pc.close()
        donor_keys = {k for k, _ in cluster.engines[0].snapshot()}
        joiner_keys = {k for k, _ in cluster.engines[2].snapshot()}
        cluster.kill(0)
        cluster.kill(2)
        # Restart both (engines survive in-process as the disk image; the
        # boot configs still describe the PRE-split world).
        cluster.start_node(0)
        cluster.start_node(2)
        for i, pid in ((0, 0), (2, 2)):
            node = cluster.nodes[i]
            assert node._partmap.epoch == 2, f"node {i} lost the epoch"
            assert node._partition_id == pid
            with cluster.client(i) as c:
                m = c.partition_map()
            assert m.epoch == 2 and m.count == 3
        assert {k for k, _ in cluster.engines[0].snapshot()} == donor_keys
        assert {k for k, _ in cluster.engines[2].snapshot()} == joiner_keys
        # A fresh smart client routes the split world correctly.
        pc = PartitionedClient([cluster.addr[1]], timeout=5).connect()
        for k in list(kv)[::9]:
            assert pc.get(k) == kv[k]
        pc.close()
    finally:
        cluster.close()


def test_boot_foreign_sweep_drops_moved_residue(tmp_path):
    """A donor killed between the epoch persist and the moved-range drop
    restarts with moved keys still in its engine: the boot sweep must
    quiet-drop exactly the foreign residue, restoring single ownership."""
    cluster = RebalCluster(tmp_path)
    try:
        pc = PartitionedClient([cluster.addr[0]], timeout=5).connect()
        _seed(pc)
        assert cluster.split().startswith("OK")
        assert wait_for(
            lambda: cluster.donor_state() == "done", timeout=60
        )
        pc.close()
        # Recreate the crash window: put the (already-moved) joiner keys
        # back into the donor's engine, as if the drop never ran.
        moved = list(cluster.engines[2].snapshot())
        assert moved
        for k, v in moved:
            cluster.engines[0].set(k, v)
        cluster.kill(0)
        cluster.start_node(0)
        donor_keys = {k for k, _ in cluster.engines[0].snapshot()}
        assert not donor_keys & {k for k, _ in moved}, (
            "boot sweep left double-owned residue"
        )
    finally:
        cluster.close()


# ------------------------------------------------- sibling fence plane


def test_sibling_fence_ttl_expiry_restores_writes():
    """A sibling fenced by a donor that then dies must not refuse moving-
    range writes forever: the TTL expires, the fence clears, the peer
    probe finds its replica group still at epoch E (rollback verdict),
    and the sibling serves writes again at the old epoch."""
    rec = get_recorder()
    rec.clear()
    ports = free_ports(3)
    addrs = [f"127.0.0.1:{p}" for p in ports]
    spec = f"0={addrs[0]};1={addrs[1]},{addrs[2]}"
    nodes, servers, engines = [], [], []
    try:
        for i, pid in ((0, 0), (1, 1), (2, 1)):
            cfg = Config()
            cfg.host = "127.0.0.1"
            cfg.port = ports[i]
            cfg.cluster.partitions = 2
            cfg.cluster.partition_id = pid
            cfg.cluster.partition_map = spec
            cfg.anti_entropy.engine = "cpu"
            cfg.anti_entropy.interval_seconds = 3600.0
            eng = NativeEngine("mem")
            srv = NativeServer(eng, "127.0.0.1", ports[i])
            srv.start()
            node = ClusterNode(cfg, eng, srv)
            node.start()
            engines.append(eng)
            servers.append(srv)
            nodes.append(node)
        sibling = nodes[2]  # second replica of partition 1
        pmap = sibling._partmap
        base, root, depth, path = pmap.moving_range(1)
        # A partition-1 key inside the moving cell.
        k = next(
            f"fence:{i}"
            for i in range(10_000)
            if key_in_range(f"fence:{i}", base, root, depth, path)
        )
        with MerkleKVClient("127.0.0.1", ports[2], timeout=5.0) as c:
            assert c.set(k, "before")
            resp = c.rebalance(
                f"FENCE 2 {base} {root} {depth} {path} 400"
            )
            assert resp == "OK fenced"
            with pytest.raises(ServerBusyError):
                c.set(k, "during-fence")
            assert c.get(k) == "before"  # reads open throughout
            # TTL expiry: writes come back without any COMMIT/ABORT.
            deadline = time.time() + 10
            while time.time() < deadline:
                try:
                    assert c.set(k, "after-expiry")
                    break
                except ServerBusyError:
                    time.sleep(0.1)
            else:
                pytest.fail("fence never expired")
        # The probe reached the group peer (nodes[1], still at epoch 1)
        # and recorded the rollback verdict; nothing was adopted.
        assert wait_for(
            lambda: any(
                e.kind == "rebalance_fence_rollback" for e in rec.last(0)
            ),
            timeout=15,
        )
        assert sibling._partmap.epoch == 1
        assert {e.kind for e in rec.last(0)} >= {
            "rebalance_fenced",
            "rebalance_fence_expired",
        }
    finally:
        for n in nodes:
            n.stop()
        for s in servers:
            s.close()
        for e in engines:
            e.close()


def test_router_serves_dumb_clients_through_live_split(tmp_path):
    """Satellite: the thin router's bounded MOVED/BUSY retry
    (PARTITION_MOVED policy) serves a dumb client straight through a
    live split — zero client-visible errors during the fence + flip,
    and the SAME router connection reads every key (including the moved
    range) after the epoch lands."""
    from merklekv_tpu.cluster.router import PartitionRouter

    cluster = RebalCluster(tmp_path)
    router = None
    errors: list = []
    try:
        router = PartitionRouter(seeds=[cluster.addr[0]]).start()
        kv = {f"rt:{i:04d}": f"v{i}" for i in range(200)}
        with MerkleKVClient("127.0.0.1", router.port, timeout=10) as rc:
            for k, v in kv.items():
                rc.set(k, v)

            stop = threading.Event()

            def storm():
                try:
                    c = MerkleKVClient(
                        "127.0.0.1", router.port, timeout=10
                    ).connect()
                    try:
                        i = 0
                        while not stop.is_set():
                            k = f"rt:{i % 200:04d}"
                            c.set(k, kv[k])  # same value: keyset stable
                            i += 1
                            time.sleep(0.002)
                    finally:
                        c.close()
                except BaseException as e:
                    errors.append(e)

            t = threading.Thread(target=storm, daemon=True)
            t.start()
            time.sleep(0.05)
            assert cluster.split().startswith("OK")
            assert wait_for(
                lambda: cluster.donor_state() in ("done", "failed"),
                timeout=60,
            )
            assert cluster.donor_state() == "done"
            time.sleep(0.3)
            stop.set()
            t.join(timeout=10)
            assert not errors, f"dumb client saw: {errors[0]!r}"

            assert all(rc.get(k) == v for k, v in kv.items())
            m = rc.partition_map()
            assert m.epoch == 2 and m.count == 3
    finally:
        if router is not None:
            router.stop()
        cluster.close()

"""Multi-node replication on one host (reference test_replication.py model).

Spins up N embedded native servers in this process, all joined through a
self-hosted TcpBroker (the reference points multiple server processes at a
real MQTT broker; same topology, no egress). Convergence is asserted by
polling GETs with a latency budget — but ours is milliseconds, not the
reference's 3-5 s public-broker budget.
"""

import statistics
import time
import uuid

import pytest

from merklekv_tpu.client import MerkleKVClient
from merklekv_tpu.cluster.change_event import (
    ChangeEvent,
    OpKind,
    decode_events,
    encode_batch_cbor,
    encode_cbor,
)
from merklekv_tpu.cluster.node import ClusterNode
from merklekv_tpu.cluster.transport import TcpBroker, TcpTransport
from merklekv_tpu.config import Config
from merklekv_tpu.native_bindings import NativeEngine, NativeServer


class Node:
    """One embedded server + cluster control plane."""

    def __init__(self, broker: TcpBroker, topic: str, node_id: str,
                 batch_max_events: int = 512):
        self.engine = NativeEngine("mem")
        self.server = NativeServer(self.engine, "127.0.0.1", 0)
        self.server.start()
        cfg = Config()
        cfg.replication.enabled = True
        cfg.replication.mqtt_broker = broker.host
        cfg.replication.mqtt_port = broker.port
        cfg.replication.topic_prefix = topic
        cfg.replication.client_id = node_id
        cfg.replication.peer_list = ["a", "b"]
        cfg.replication.batch_max_events = batch_max_events
        self.cluster = ClusterNode(cfg, self.engine, self.server)
        self.cluster.start()
        self.client = MerkleKVClient("127.0.0.1", self.server.port).connect()

    def close(self):
        self.client.close()
        self.cluster.stop()
        self.server.close()
        self.engine.close()


@pytest.fixture
def broker():
    b = TcpBroker()
    yield b
    b.close()


@pytest.fixture
def pair(broker):
    topic = f"test-{uuid.uuid4().hex[:8]}"  # uniquified per test run
    n1 = Node(broker, topic, "node-1")
    n2 = Node(broker, topic, "node-2")
    yield n1, n2
    n1.close()
    n2.close()


def wait_for(fn, timeout=5.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def test_set_propagates(pair):
    n1, n2 = pair
    n1.client.set("rk", "rv")
    assert wait_for(lambda: n2.client.get("rk") == "rv")


def test_delete_propagates(pair):
    n1, n2 = pair
    n1.client.set("dk", "dv")
    assert wait_for(lambda: n2.client.get("dk") == "dv")
    n1.client.delete("dk")
    assert wait_for(lambda: n2.client.get("dk") is None)


def test_numeric_and_string_ops_replicate_post_op(pair):
    n1, n2 = pair
    n1.client.increment("num", 5)
    n1.client.increment("num", 2)
    assert wait_for(lambda: n2.client.get("num") == "7")
    n1.client.append("s", "ab")
    n1.client.prepend("s", "x")
    assert wait_for(lambda: n2.client.get("s") == "xab")


def test_bidirectional(pair):
    n1, n2 = pair
    n1.client.set("from1", "a")
    n2.client.set("from2", "b")
    assert wait_for(lambda: n2.client.get("from1") == "a")
    assert wait_for(lambda: n1.client.get("from2") == "b")


def test_no_echo_loop(pair):
    n1, n2 = pair
    n1.client.set("loop", "v")
    assert wait_for(lambda: n2.client.get("loop") == "v")
    time.sleep(0.2)  # would re-publish within this window if looping
    assert n1.cluster.replicator.received <= 1
    # Applied remote writes must not re-enter node-2's publish queue.
    assert n2.cluster.replicator.published == 0


def test_concurrent_writers_converge(pair):
    n1, n2 = pair
    for i in range(50):
        (n1 if i % 2 else n2).client.set(f"cw{i}", f"v{i}")

    def converged():
        for i in range(50):
            if n1.client.get(f"cw{i}") != f"v{i}":
                return False
            if n2.client.get(f"cw{i}") != f"v{i}":
                return False
        return True

    assert wait_for(converged)
    # Merkle roots agree after convergence.
    assert n1.client.hash() == n2.client.hash()


def test_malformed_messages_tolerated(pair, broker):
    n1, n2 = pair
    topic = n1.cluster._cfg.replication.topic_prefix + "/events"
    rogue = TcpTransport(broker.host, broker.port)
    rogue.publish(topic, b"\xff\xfenot an event")
    rogue.publish(topic, b"")
    n1.client.set("after-garbage", "ok")
    assert wait_for(lambda: n2.client.get("after-garbage") == "ok")
    assert n2.cluster.replicator.decode_errors >= 1
    rogue.close()


def test_stale_event_rejected_by_lww(pair):
    n1, n2 = pair
    n1.client.set("lww", "current")
    assert wait_for(lambda: n2.client.get("lww") == "current")
    # Inject an old event directly (simulates a delayed redelivery).
    stale = ChangeEvent(op=OpKind.SET, key="lww", val=b"ancient", ts=1,
                        src="node-3")
    n2.cluster.replicator._on_message("t", encode_cbor(stale))
    assert n2.client.get("lww") == "current"


def test_replicate_status_commands(pair):
    n1, _ = pair
    assert n1.client.replicate("status") == "REPLICATION enabled 2 nodes"
    assert n1.client.replicate("disable") == "OK"
    assert n1.client.replicate("status") == "REPLICATION disabled"
    assert n1.client.replicate("enable") == "OK"
    assert n1.client.replicate("status") == "REPLICATION enabled 2 nodes"


def test_node_restart_catches_up_via_sync(broker):
    """Reference scenario test_replication.py:556 — a restarted node misses
    events; anti-entropy repairs it."""
    topic = f"test-{uuid.uuid4().hex[:8]}"
    n1 = Node(broker, topic, "node-1")
    n2 = Node(broker, topic, "node-2")
    try:
        n1.client.set("pre", "1")
        assert wait_for(lambda: n2.client.get("pre") == "1")
        # "Restart" node 2: drop its state while offline.
        n2.cluster.stop()
        n2.engine.truncate()
        n1.client.set("while-down", "2")
        time.sleep(0.1)
        # Node 2 back up with a fresh control plane.
        n2.cluster = ClusterNode(n2.cluster._cfg, n2.engine, n2.server)
        n2.cluster.start()
        # Replication alone can't recover the missed event...
        assert n2.client.get("while-down") is None
        # ...anti-entropy does.
        n2.client.sync_with("127.0.0.1", n1.server.port)
        assert n2.client.get("while-down") == "2"
        assert n2.client.get("pre") == "1"
        assert n1.client.hash() == n2.client.hash()
    finally:
        n1.close()
        n2.close()


def test_interleaved_events_and_repairs_converge_any_order():
    """Property: replication events and anti-entropy repairs share ONE LWW
    ordering (the engine's, under the shard lock), so applying the same
    mixed batch in any order — with an applier restart mid-stream — lands
    every engine in the same final state."""
    import random

    from merklekv_tpu.cluster.applier import LWWApplier

    def make_applier(engine):
        # The replicator's engine-backed wiring (replicator.py), minus the
        # transport: conditional ops + store-seeded floor.
        return LWWApplier(
            engine.set,
            lambda k: engine.delete(k),
            set_ts_fn=lambda k, v, ts: engine.set_if_newer(k, v, ts),
            del_ts_fn=lambda k, ts: engine.delete_if_newer(k, ts),
            store_ts_fn=lambda k: max(
                engine.get_ts(k) or 0, engine.tombstone_ts(k) or 0
            ),
        )

    # A mixed history over 3 keys: replication SET/DEL events (distinct ts,
    # distinct op_ids) and sync-style repairs (set_if_newer/del_if_newer).
    ops = []
    rng = random.Random(11)
    for i, ts in enumerate(rng.sample(range(100, 1000), 12)):
        key = f"pk{i % 3}"
        kind = rng.choice(["ev_set", "ev_del", "repair_set", "repair_del"])
        ops.append((kind, key, ts, i))

    def run(order, restart_at):
        eng = NativeEngine("mem")
        applier = make_applier(eng)
        try:
            for step, idx in enumerate(order):
                if step == restart_at:
                    applier = make_applier(eng)  # restart: in-mem maps wiped
                kind, key, ts, i = ops[idx]
                if kind == "ev_set":
                    applier.apply(ChangeEvent(
                        op=OpKind.SET, key=key, val=b"ev%d" % i, ts=ts,
                        src="peer", op_id=b"%016d" % i,
                    ))
                elif kind == "ev_del":
                    applier.apply(ChangeEvent(
                        op=OpKind.DEL, key=key, val=None, ts=ts,
                        src="peer", op_id=b"%016d" % i,
                    ))
                elif kind == "repair_set":
                    eng.set_if_newer(key.encode(), b"rp%d" % i, ts)
                else:
                    eng.delete_if_newer(key.encode(), ts)
            return {k: v for k, v in eng.snapshot()}
        finally:
            eng.close()

    base_order = list(range(len(ops)))
    reference_state = run(base_order, restart_at=len(ops) // 2)
    for trial in range(8):
        order = base_order[:]
        random.Random(trial).shuffle(order)
        state = run(order, restart_at=random.Random(trial + 100).randrange(len(ops)))
        assert state == reference_state, f"order {order} diverged"


def test_non_utf8_key_replicates(pair, broker):
    """A key whose bytes are not valid UTF-8 must replicate end-to-end:
    surrogateescape decode (replicator._to_event), surrogateescape codec
    round-trip, and surrogateescape re-encode in the applier. Historically
    the strict encode raised and the transport guard ate the event."""
    n1, n2 = pair
    raw_key = b"bin\xff\xfekey"
    ev = ChangeEvent(
        op=OpKind.SET,
        key=raw_key.decode("utf-8", "surrogateescape"),
        val=b"binval",
        ts=time.time_ns(),
        src="rogue",
    )
    topic = n1.cluster._cfg.replication.topic_prefix + "/events"
    rogue = TcpTransport(broker.host, broker.port)
    try:
        rogue.publish(topic, encode_cbor(ev))
        assert wait_for(lambda: n2.engine.get(raw_key) == b"binval")
        assert wait_for(lambda: n1.engine.get(raw_key) == b"binval")
        # The event must have been applied, not swallowed by the callback
        # guard (the pre-fix failure mode).
        assert n2.cluster.replicator._transport.callback_errors == 0
    finally:
        rogue.close()


def test_equal_ts_cross_writer_converges_without_sync():
    """Two replicas apply the same pair of equal-ts events from different
    writers in OPPOSITE orders. The engine's digest tie-break (set_if_newer)
    must land both on the same value — replication alone converges, no
    anti-entropy needed (historically the applier's in-memory op_id
    tie-break made this order-dependent after a restart)."""
    from merklekv_tpu.cluster.applier import LWWApplier
    from merklekv_tpu.native_bindings import NativeEngine

    ts = time.time_ns()
    ev_a = ChangeEvent(op=OpKind.SET, key="eq", val=b"alpha", ts=ts,
                       src="w1", op_id=b"\x01" * 16)
    ev_b = ChangeEvent(op=OpKind.SET, key="eq", val=b"beta", ts=ts,
                       src="w2", op_id=b"\x02" * 16)

    def engine_applier(engine):
        return LWWApplier(
            engine.set,
            lambda k: engine.delete(k),
            set_ts_fn=lambda k, v, t: engine.set_if_newer(k, v, t),
            del_ts_fn=lambda k, t: engine.delete_if_newer(k, t),
            store_ts_fn=lambda k: max(
                engine.get_ts(k) or 0, engine.tombstone_ts(k) or 0
            ),
        )

    e1, e2 = NativeEngine("mem"), NativeEngine("mem")
    try:
        a1, a2 = engine_applier(e1), engine_applier(e2)
        a1.apply(ev_a)
        a1.apply(ev_b)
        a2.apply(ev_b)
        a2.apply(ev_a)
        assert e1.get(b"eq") == e2.get(b"eq")
        assert e1.get(b"eq") in (b"alpha", b"beta")
    finally:
        e1.close()
        e2.close()


# ------------------------------------------------------- batched pipeline

class RecordingTransport:
    """Transport double capturing publishes (no wire, no broker)."""

    def __init__(self):
        self.published: list[bytes] = []

    def publish(self, topic, payload):
        self.published.append(payload)

    def subscribe(self, prefix, cb):
        pass

    def unsubscribe(self, cb):
        pass

    def close(self):
        pass


@pytest.fixture
def bare_replicator():
    """Replicator over a recording transport, drain thread NOT started —
    flush() is driven by the test, so framing is deterministic."""
    from merklekv_tpu.cluster.replicator import Replicator

    engine = NativeEngine("mem")
    server = NativeServer(engine, "127.0.0.1", 0)
    server.start()
    transport = RecordingTransport()

    def make(**kw):
        rep = Replicator(engine, server, transport, node_id="src-1", **kw)
        server.enable_events(True)
        return rep

    client = MerkleKVClient("127.0.0.1", server.port).connect()
    yield make, transport, client, engine
    client.close()
    server.close()
    engine.close()


def test_one_drained_batch_is_one_coalesced_frame(bare_replicator):
    make, transport, client, _engine = bare_replicator
    rep = make()
    client.set("k1", "a")
    client.set("k1", "b")
    client.set("k2", "x")
    client.delete("k1")
    rep.flush()
    # ONE wire frame for the whole drained batch, coalesced per key: the
    # two superseded k1 ops are gone, the final DEL and the k2 SET remain.
    assert len(transport.published) == 1
    events = decode_events(transport.published[0])
    assert {(e.key, e.op) for e in events} == {
        ("k1", OpKind.DEL), ("k2", OpKind.SET),
    }
    assert all(e.src == "src-1" for e in events)
    assert rep.coalesced == 2
    assert rep.published == 2


def test_frame_splits_under_batch_caps(bare_replicator):
    make, transport, client, _engine = bare_replicator
    rep = make(batch_max_events=4)
    for i in range(10):
        client.set(f"s{i}", "v")
    rep.flush()
    assert len(transport.published) == 3  # 4 + 4 + 2
    sizes = [len(decode_events(p)) for p in transport.published]
    assert sizes == [4, 4, 2]
    # Byte cap splits too: ~300 B of value per event against a 1 KiB cap.
    transport.published.clear()
    rep2 = make(batch_max_events=512, batch_max_bytes=1024)
    for i in range(8):
        client.set(f"b{i}", "x" * 300)
    rep2.flush()
    assert len(transport.published) >= 3
    assert sum(len(decode_events(p)) for p in transport.published) == 8


def test_per_event_mode_emits_legacy_payloads(bare_replicator):
    """batch_max_events <= 1 keeps the pre-envelope wire format: one
    single-event CBOR payload per write, decodable by decode_any — the
    compat mode un-batched peers understand."""
    from merklekv_tpu.cluster.change_event import decode_any

    make, transport, client, _engine = bare_replicator
    rep = make(batch_max_events=1)
    client.set("l1", "a")
    client.set("l2", "b")
    rep.flush()
    assert len(transport.published) == 2
    for p in transport.published:
        ev = decode_any(p)  # old decoder path, no envelope
        assert ev.src == "src-1"


def test_mixed_version_interop_converges(broker):
    """An un-batched (legacy single-event) publisher and a batching
    publisher in one cluster converge on identical roots — the
    mixed-version wire-compat contract."""
    topic = f"mv-{uuid.uuid4().hex[:8]}"
    legacy = Node(broker, topic, "legacy-node", batch_max_events=1)
    batched = Node(broker, topic, "batched-node")  # default 512
    try:
        for i in range(40):
            legacy.client.set(f"leg{i}", f"lv{i}")
            batched.client.set(f"bat{i}", f"bv{i}")
        legacy.client.delete("leg3")
        batched.client.delete("bat7")

        def converged():
            return (
                legacy.client.get("bat39") == "bv39"
                and batched.client.get("leg39") == "lv39"
                and legacy.client.get("bat7") is None
                and batched.client.get("leg3") is None
                and legacy.client.hash() == batched.client.hash()
            )

        assert wait_for(converged, timeout=15)
        # The legacy node really decoded envelope-less payloads only from
        # itself; the batched node's envelopes reached it as whole frames.
        assert legacy.cluster.replicator.received >= 40
        assert batched.cluster.replicator.received >= 40
        assert legacy.cluster.replicator.decode_errors == 0
        assert batched.cluster.replicator.decode_errors == 0
    finally:
        legacy.close()
        batched.close()


def test_malformed_and_duplicate_frames_never_crash_applier(pair):
    n1, n2 = pair
    rep = n2.cluster.replicator
    base_errors = rep.decode_errors
    evs = [
        ChangeEvent(op=OpKind.SET, key=f"mf{i}", val=b"v%d" % i,
                    ts=time.time_ns(), src="rogue")
        for i in range(5)
    ]
    frame = encode_batch_cbor(evs, "rogue")
    # Truncated frames: counted as decode errors, never applied partially.
    for cut in (1, 7, len(frame) // 2, len(frame) - 1):
        rep._on_message("t", frame[:cut])
    # Unknown envelope version: refused whole.
    rep._on_message("t", frame.replace(b"\x61v\x01", b"\x61v\x09", 1))
    assert rep.decode_errors == base_errors + 5
    assert n2.engine.get(b"mf0") is None  # nothing leaked from bad frames
    # The intact frame applies...
    rep._on_message("t", frame)
    assert n2.engine.get(b"mf4") == b"v4"
    applied_before = rep.applier.applied
    # ...and a DUPLICATE delivery of the same frame dedupes on op_id.
    rep._on_message("t", frame)
    assert rep.applier.applied == applied_before
    assert rep.applier.skipped_dup >= 5
    # The pipeline still replicates after all that garbage.
    n1.client.set("after-fuzz", "ok")
    assert wait_for(lambda: n2.client.get("after-fuzz") == "ok")


def test_single_set_replicates_well_under_old_poll_floor(pair):
    """Satellite regression: the drain thread parks on the native queue's
    notify, so a lone SET replicates in the wake+publish+apply latency —
    the old 5 ms drain poll put a ~2.5 ms floor (poll/2) on the MEDIAN
    before any wire or apply cost. Median over 21 singles must land well
    under the old floor (generous 2 ms bound for CI jitter; the typical
    wake path is a few hundred µs)."""
    n1, n2 = pair
    n1.client.set("warm", "x")
    assert wait_for(lambda: n2.engine.get(b"warm") == b"x")
    lat = []
    for i in range(21):
        key = f"lat{i}".encode()
        t0 = time.perf_counter()
        n1.client.set(f"lat{i}", "v")
        deadline = time.time() + 5
        while n2.engine.get(key) != b"v":
            if time.time() > deadline:
                pytest.fail(f"event {i} never replicated")
            time.sleep(0.0001)
        lat.append(time.perf_counter() - t0)
    assert statistics.median(lat) < 0.002, sorted(lat)


def test_frame_of_k_writes_is_one_mirror_dispatch(pair):
    """Acceptance: k remote writes arriving as ONE frame cost exactly one
    incremental-tree program dispatch on the receiver's device mirror
    (batched staging + one flush at the next root read), and the device
    root stays bit-identical to the engine root."""
    n1, n2 = pair
    k = 16
    for i in range(k):
        n1.client.set(f"dk{i:02d}", "v0")
    assert wait_for(lambda: n2.engine.get(b"dk15") == b"v0")
    # Warm n2's device mirror (first device use compiles kernels).
    assert wait_for(
        lambda: n2.cluster.device_root_hex() is not None, timeout=90
    )
    st = n2.cluster._mirror.state
    base_inc = st.incremental_batches
    base_struct = st.structural_batches
    ts = time.time_ns()
    frame = encode_batch_cbor(
        [
            ChangeEvent(op=OpKind.SET, key=f"dk{i:02d}", val=b"v1",
                        ts=ts + i, src="rogue")
            for i in range(k)
        ],
        "rogue",
    )
    n2.cluster.replicator._on_message("t", frame)
    assert n2.engine.get(b"dk00") == b"v1"
    # force=True publishes the staged frame through the pump (the unforced
    # path serves the previous snapshot until the pump's next cycle).
    root = n2.cluster.device_root_hex(force=True)
    assert st.incremental_batches == base_inc + 1  # ONE scatter program
    assert st.structural_batches == base_struct
    assert root == n2.engine.merkle_root().hex()


def test_batched_replication_throughput_sanity(pair):
    """Tier-1 throughput floor over the full batched path (CPU backend,
    loose bound — the real A/B number lives in bench.py's
    replicated_write_throughput scenario): ingest -> converged engine
    roots at a rate no slouch CI box should miss by 10x."""
    n1, n2 = pair
    n = 4000
    t0 = time.perf_counter()
    for base in range(0, n, 100):
        n1.client.mset(
            {f"tp{i:06d}": f"v{i}" for i in range(base, base + 100)}
        )
    deadline = time.time() + 30
    while time.time() < deadline:
        ra, rb = n1.engine.merkle_root(), n2.engine.merkle_root()
        if ra is not None and ra == rb:
            break
        time.sleep(0.002)
    dt = time.perf_counter() - t0
    assert n1.engine.merkle_root() == n2.engine.merkle_root()
    rate = n / dt
    assert rate > 800, f"batched pipeline too slow: {rate:.0f} events/s"
    from merklekv_tpu.utils.tracing import get_metrics

    snap = get_metrics().snapshot()
    hist = snap["histograms"].get("replicator.batch_size")
    assert hist is not None and hist["count"] >= 1  # frames were observed
    assert "replicator.batch_size" in snap["size_histograms"]


class LossyTransport:
    """Transport wrapper dropping a deterministic fraction of publishes —
    frame loss on the QoS-0 fabric (VERDICT r4 item 10)."""

    def __init__(self, inner, drop_rate: float, seed: int = 7):
        import random

        self._inner = inner
        self._rng = random.Random(seed)
        self._rate = drop_rate
        self.dropped = 0
        self.passed = 0

    def publish(self, topic, payload):
        if self._rng.random() < self._rate:
            self.dropped += 1
            return  # frame lost in transit
        self.passed += 1
        self._inner.publish(topic, payload)

    def subscribe(self, prefix, cb):
        self._inner.subscribe(prefix, cb)

    def unsubscribe(self, cb):
        self._inner.unsubscribe(cb)

    def close(self):
        self._inner.close()


@pytest.mark.integration
def test_convergence_under_frame_loss(broker):
    """QoS-0 replication + periodic anti-entropy converge under heavy frame
    loss — the design argument behind dropping the reference's QoS-1
    (replication.rs:257-264) becomes a measured number. 40% of publishes
    are dropped; the anti-entropy loop (200 ms interval) must repair every
    hole. The reference's own budget for LOSSLESS propagation through a
    public broker is 3-5 s (README.md:56)."""
    from merklekv_tpu.cluster.transport import TcpTransport

    topic = f"loss-{uuid.uuid4().hex[:8]}"

    def make_node(node_id, peers):
        engine = NativeEngine("mem")
        server = NativeServer(engine, "127.0.0.1", 0)
        server.start()
        cfg = Config()
        cfg.replication.enabled = True
        cfg.replication.mqtt_broker = broker.host
        cfg.replication.mqtt_port = broker.port
        cfg.replication.topic_prefix = topic
        cfg.replication.client_id = node_id
        cfg.anti_entropy.enabled = True
        cfg.anti_entropy.interval_seconds = 0.2
        cfg.anti_entropy.peers = peers
        lossy = LossyTransport(
            TcpTransport(broker.host, broker.port), drop_rate=0.4
        )
        node = ClusterNode(cfg, engine, server, transport=lossy)
        node.start()
        client = MerkleKVClient("127.0.0.1", server.port, timeout=15).connect()
        return engine, server, node, client, lossy

    e1, s1, n1, c1, t1 = make_node("loss-1", [])
    # Node 2 periodically syncs FROM node 1 (the anti-entropy backstop).
    e2, s2, n2, c2, t2 = make_node("loss-2", [f"127.0.0.1:{s1.port}"])
    try:
        n_keys = 60
        t0 = time.time()
        for i in range(n_keys):
            c1.set(f"loss{i:03d}", f"v{i}")
        c1.delete("loss000")  # a deletion must survive loss too

        def converged():
            return c1.hash() == c2.hash()

        assert wait_for(converged, timeout=30), (
            f"no convergence: dropped={t1.dropped} passed={t1.passed}"
        )
        seconds = time.time() - t0
        # The point of the test: real loss happened AND we converged.
        assert t1.dropped > 0, "drop injector never fired"
        assert c2.get("loss001") == "v1"
        assert c2.get("loss000") is None
        # Report the number (visible with -s / in CI logs).
        print(
            f"\nconverged in {seconds:.2f}s with "
            f"{t1.dropped}/{t1.dropped + t1.passed} frames dropped"
        )
    finally:
        for cl, nd, sv, en in ((c1, n1, s1, e1), (c2, n2, s2, e2)):
            cl.close()
            nd.stop()
            sv.close()
            en.close()


def test_framed_transport_reconnects_after_broker_restart():
    """Broker restart heals the fabric without node restarts: the transport
    re-dials with backoff and events flow again (the reference's rumqttc
    behavior, replication.rs:148-166)."""
    broker = TcpBroker()
    port = broker.port
    t_pub = TcpTransport(broker.host, port)
    t_sub = TcpTransport(broker.host, port)
    got = []
    try:
        t_sub.subscribe("rc/events", lambda topic, p: got.append(p))
        time.sleep(0.05)
        t_pub.publish("rc/events", b"before")
        deadline = time.time() + 5
        while time.time() < deadline and got != [b"before"]:
            time.sleep(0.01)
        assert got == [b"before"]

        broker.close()
        # Same port: restarted broker, new process in production terms.
        deadline = time.time() + 10
        broker = None
        while time.time() < deadline and broker is None:
            try:
                broker = TcpBroker(port=port)
            except OSError:
                time.sleep(0.1)  # TIME_WAIT on the listener
        assert broker is not None, "broker could not rebind its port"
        deadline = time.time() + 15
        while time.time() < deadline and (
            t_pub.reconnects < 1 or t_sub.reconnects < 1
        ):
            time.sleep(0.05)
        assert t_pub.reconnects >= 1 and t_sub.reconnects >= 1
        from merklekv_tpu.utils.tracing import get_metrics

        assert get_metrics().snapshot()["counters"].get(
            "transport.reconnects", 0
        ) >= 2

        deadline = time.time() + 10
        while time.time() < deadline and b"after" not in got:
            t_pub.publish("rc/events", b"after")
            time.sleep(0.1)
        assert b"after" in got
    finally:
        t_pub.close()
        t_sub.close()
        if broker is not None:
            broker.close()


def test_framed_outbox_flushes_after_heal():
    """Events published WHILE the broker is down are buffered (bounded
    outbox) and delivered after the link heals — replication survives an
    outage instead of silently dropping every write in the window."""
    broker = TcpBroker()
    port = broker.port
    t_pub = TcpTransport(broker.host, port)
    t_sub = TcpTransport(broker.host, port)
    # The publisher's post-heal drain must not beat the subscriber's
    # reconnect (the broker fans only to CONNECTED clients); stagger the
    # publisher's first retry so the subscriber deterministically wins.
    t_pub._BACKOFF_FIRST = 1.5
    got = []
    try:
        t_sub.subscribe("ob/events", lambda topic, p: got.append(p))
        time.sleep(0.05)
        broker.close()
        # Wait for the DETECTED-down state: events sent into the kernel
        # buffer of a dead-but-undetected link are inherently lossy
        # without broker acks; the outbox guarantee starts at detection.
        deadline = time.time() + 5
        while time.time() < deadline and not (
            t_pub.link_down and t_sub.link_down
        ):
            time.sleep(0.02)
        assert t_pub.link_down and t_sub.link_down
        for i in range(5):
            t_pub.publish("ob/events", b"during-%d" % i)
        # Nothing could have been delivered: the broker is down.
        assert got == []
        deadline = time.time() + 10
        broker = None
        while time.time() < deadline and broker is None:
            try:
                broker = TcpBroker(port=port)
            except OSError:
                time.sleep(0.1)
        assert broker is not None, "broker could not rebind its port"
        deadline = time.time() + 15
        while time.time() < deadline and len(got) < 5:
            time.sleep(0.05)
        assert got == [b"during-%d" % i for i in range(5)], got
    finally:
        t_pub.close()
        t_sub.close()
        if broker is not None:
            broker.close()


def test_outbox_overflow_drops_oldest_and_counts():
    """The outage buffer is bounded: overflow drops the OLDEST event (LWW:
    newer state supersedes older) and counts the drop."""
    from merklekv_tpu.cluster import transport as tmod

    broker = TcpBroker()
    t = TcpTransport(broker.host, broker.port)
    try:
        t.link_down = True  # force the enqueue path; no wire traffic
        n_extra = 7
        for i in range(tmod.OUTBOX_LIMIT + n_extra):
            t.publish("of/events", b"e-%d" % i)
        assert len(t._outbox) == tmod.OUTBOX_LIMIT
        assert t.outbox_dropped == n_extra
        # Oldest dropped: the queue starts at e-<n_extra>.
        assert t._outbox[0] == ("of/events", b"e-%d" % n_extra)
        assert t._outbox[-1] == (
            "of/events", b"e-%d" % (tmod.OUTBOX_LIMIT + n_extra - 1)
        )
    finally:
        t.link_down = False
        t.close()
        broker.close()


def test_replicate_disable_actually_detaches_applier():
    """Regression: transports remove subscriptions by callback IDENTITY,
    and ``self._on_message`` is a fresh bound-method object per attribute
    access — the replicator must subscribe/unsubscribe with ONE pinned
    object, or a stopped ("REPLICATE disable"d) node keeps applying every
    inbound frame. Found by end-to-end verification of PR 7."""
    import uuid as _uuid

    from merklekv_tpu.cluster.node import ClusterNode
    from merklekv_tpu.cluster.transport import TcpBroker
    from merklekv_tpu.config import Config
    from merklekv_tpu.native_bindings import NativeEngine, NativeServer

    broker = TcpBroker()
    topic = f"dis-{_uuid.uuid4().hex[:8]}"
    made = []
    try:
        for name in ("dis-a", "dis-b"):
            eng = NativeEngine("mem")
            srv = NativeServer(eng, "127.0.0.1", 0)
            srv.start()
            cfg = Config()
            cfg.replication.enabled = True
            cfg.replication.mqtt_broker = broker.host
            cfg.replication.mqtt_port = broker.port
            cfg.replication.topic_prefix = topic
            cfg.replication.client_id = name
            cfg.anti_entropy.engine = "cpu"
            node = ClusterNode(cfg, eng, srv)
            node.start()
            made.append((eng, srv, node))
        (eng_a, srv_a, node_a), (eng_b, srv_b, node_b) = made

        from merklekv_tpu.client import MerkleKVClient

        with MerkleKVClient("127.0.0.1", srv_a.port) as c:
            c.set("pre", "1")
        deadline = time.time() + 10
        while time.time() < deadline and eng_b.dbsize() < 1:
            time.sleep(0.02)
        assert eng_b.dbsize() == 1

        with MerkleKVClient("127.0.0.1", srv_b.port) as c:
            assert c.replicate("disable") == "OK"
        with MerkleKVClient("127.0.0.1", srv_a.port) as c:
            for i in range(20):
                c.set(f"post:{i}", "x")
        deadline = time.time() + 10
        while time.time() < deadline and eng_a.dbsize() < 21:
            time.sleep(0.02)
        time.sleep(0.5)  # give any (buggy) residual subscription a window
        assert eng_b.dbsize() == 1, "disabled node still applied frames"
    finally:
        for eng, srv, node in reversed(made):
            node.stop()
            srv.close()
            eng.close()
        broker.close()

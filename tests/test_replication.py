"""Multi-node replication on one host (reference test_replication.py model).

Spins up N embedded native servers in this process, all joined through a
self-hosted TcpBroker (the reference points multiple server processes at a
real MQTT broker; same topology, no egress). Convergence is asserted by
polling GETs with a latency budget — but ours is milliseconds, not the
reference's 3-5 s public-broker budget.
"""

import time
import uuid

import pytest

from merklekv_tpu.client import MerkleKVClient
from merklekv_tpu.cluster.change_event import ChangeEvent, OpKind, encode_cbor
from merklekv_tpu.cluster.node import ClusterNode
from merklekv_tpu.cluster.transport import TcpBroker, TcpTransport
from merklekv_tpu.config import Config
from merklekv_tpu.native_bindings import NativeEngine, NativeServer


class Node:
    """One embedded server + cluster control plane."""

    def __init__(self, broker: TcpBroker, topic: str, node_id: str):
        self.engine = NativeEngine("mem")
        self.server = NativeServer(self.engine, "127.0.0.1", 0)
        self.server.start()
        cfg = Config()
        cfg.replication.enabled = True
        cfg.replication.mqtt_broker = broker.host
        cfg.replication.mqtt_port = broker.port
        cfg.replication.topic_prefix = topic
        cfg.replication.client_id = node_id
        cfg.replication.peer_list = ["a", "b"]
        self.cluster = ClusterNode(cfg, self.engine, self.server)
        self.cluster.start()
        self.client = MerkleKVClient("127.0.0.1", self.server.port).connect()

    def close(self):
        self.client.close()
        self.cluster.stop()
        self.server.close()
        self.engine.close()


@pytest.fixture
def broker():
    b = TcpBroker()
    yield b
    b.close()


@pytest.fixture
def pair(broker):
    topic = f"test-{uuid.uuid4().hex[:8]}"  # uniquified per test run
    n1 = Node(broker, topic, "node-1")
    n2 = Node(broker, topic, "node-2")
    yield n1, n2
    n1.close()
    n2.close()


def wait_for(fn, timeout=5.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def test_set_propagates(pair):
    n1, n2 = pair
    n1.client.set("rk", "rv")
    assert wait_for(lambda: n2.client.get("rk") == "rv")


def test_delete_propagates(pair):
    n1, n2 = pair
    n1.client.set("dk", "dv")
    assert wait_for(lambda: n2.client.get("dk") == "dv")
    n1.client.delete("dk")
    assert wait_for(lambda: n2.client.get("dk") is None)


def test_numeric_and_string_ops_replicate_post_op(pair):
    n1, n2 = pair
    n1.client.increment("num", 5)
    n1.client.increment("num", 2)
    assert wait_for(lambda: n2.client.get("num") == "7")
    n1.client.append("s", "ab")
    n1.client.prepend("s", "x")
    assert wait_for(lambda: n2.client.get("s") == "xab")


def test_bidirectional(pair):
    n1, n2 = pair
    n1.client.set("from1", "a")
    n2.client.set("from2", "b")
    assert wait_for(lambda: n2.client.get("from1") == "a")
    assert wait_for(lambda: n1.client.get("from2") == "b")


def test_no_echo_loop(pair):
    n1, n2 = pair
    n1.client.set("loop", "v")
    assert wait_for(lambda: n2.client.get("loop") == "v")
    time.sleep(0.2)  # would re-publish within this window if looping
    assert n1.cluster.replicator.received <= 1
    # Applied remote writes must not re-enter node-2's publish queue.
    assert n2.cluster.replicator.published == 0


def test_concurrent_writers_converge(pair):
    n1, n2 = pair
    for i in range(50):
        (n1 if i % 2 else n2).client.set(f"cw{i}", f"v{i}")

    def converged():
        for i in range(50):
            if n1.client.get(f"cw{i}") != f"v{i}":
                return False
            if n2.client.get(f"cw{i}") != f"v{i}":
                return False
        return True

    assert wait_for(converged)
    # Merkle roots agree after convergence.
    assert n1.client.hash() == n2.client.hash()


def test_malformed_messages_tolerated(pair, broker):
    n1, n2 = pair
    topic = n1.cluster._cfg.replication.topic_prefix + "/events"
    rogue = TcpTransport(broker.host, broker.port)
    rogue.publish(topic, b"\xff\xfenot an event")
    rogue.publish(topic, b"")
    n1.client.set("after-garbage", "ok")
    assert wait_for(lambda: n2.client.get("after-garbage") == "ok")
    assert n2.cluster.replicator.decode_errors >= 1
    rogue.close()


def test_stale_event_rejected_by_lww(pair):
    n1, n2 = pair
    n1.client.set("lww", "current")
    assert wait_for(lambda: n2.client.get("lww") == "current")
    # Inject an old event directly (simulates a delayed redelivery).
    stale = ChangeEvent(op=OpKind.SET, key="lww", val=b"ancient", ts=1,
                        src="node-3")
    n2.cluster.replicator._on_message("t", encode_cbor(stale))
    assert n2.client.get("lww") == "current"


def test_replicate_status_commands(pair):
    n1, _ = pair
    assert n1.client.replicate("status") == "REPLICATION enabled 2 nodes"
    assert n1.client.replicate("disable") == "OK"
    assert n1.client.replicate("status") == "REPLICATION disabled"
    assert n1.client.replicate("enable") == "OK"
    assert n1.client.replicate("status") == "REPLICATION enabled 2 nodes"


def test_node_restart_catches_up_via_sync(broker):
    """Reference scenario test_replication.py:556 — a restarted node misses
    events; anti-entropy repairs it."""
    topic = f"test-{uuid.uuid4().hex[:8]}"
    n1 = Node(broker, topic, "node-1")
    n2 = Node(broker, topic, "node-2")
    try:
        n1.client.set("pre", "1")
        assert wait_for(lambda: n2.client.get("pre") == "1")
        # "Restart" node 2: drop its state while offline.
        n2.cluster.stop()
        n2.engine.truncate()
        n1.client.set("while-down", "2")
        time.sleep(0.1)
        # Node 2 back up with a fresh control plane.
        n2.cluster = ClusterNode(n2.cluster._cfg, n2.engine, n2.server)
        n2.cluster.start()
        # Replication alone can't recover the missed event...
        assert n2.client.get("while-down") is None
        # ...anti-entropy does.
        n2.client.sync_with("127.0.0.1", n1.server.port)
        assert n2.client.get("while-down") == "2"
        assert n2.client.get("pre") == "1"
        assert n1.client.hash() == n2.client.hash()
    finally:
        n1.close()
        n2.close()


def test_interleaved_events_and_repairs_converge_any_order():
    """Property: replication events and anti-entropy repairs share ONE LWW
    ordering (the engine's, under the shard lock), so applying the same
    mixed batch in any order — with an applier restart mid-stream — lands
    every engine in the same final state."""
    import random

    from merklekv_tpu.cluster.applier import LWWApplier

    def make_applier(engine):
        # The replicator's engine-backed wiring (replicator.py), minus the
        # transport: conditional ops + store-seeded floor.
        return LWWApplier(
            engine.set,
            lambda k: engine.delete(k),
            set_ts_fn=lambda k, v, ts: engine.set_if_newer(k, v, ts),
            del_ts_fn=lambda k, ts: engine.delete_if_newer(k, ts),
            store_ts_fn=lambda k: max(
                engine.get_ts(k) or 0, engine.tombstone_ts(k) or 0
            ),
        )

    # A mixed history over 3 keys: replication SET/DEL events (distinct ts,
    # distinct op_ids) and sync-style repairs (set_if_newer/del_if_newer).
    ops = []
    rng = random.Random(11)
    for i, ts in enumerate(rng.sample(range(100, 1000), 12)):
        key = f"pk{i % 3}"
        kind = rng.choice(["ev_set", "ev_del", "repair_set", "repair_del"])
        ops.append((kind, key, ts, i))

    def run(order, restart_at):
        eng = NativeEngine("mem")
        applier = make_applier(eng)
        try:
            for step, idx in enumerate(order):
                if step == restart_at:
                    applier = make_applier(eng)  # restart: in-mem maps wiped
                kind, key, ts, i = ops[idx]
                if kind == "ev_set":
                    applier.apply(ChangeEvent(
                        op=OpKind.SET, key=key, val=b"ev%d" % i, ts=ts,
                        src="peer", op_id=b"%016d" % i,
                    ))
                elif kind == "ev_del":
                    applier.apply(ChangeEvent(
                        op=OpKind.DEL, key=key, val=None, ts=ts,
                        src="peer", op_id=b"%016d" % i,
                    ))
                elif kind == "repair_set":
                    eng.set_if_newer(key.encode(), b"rp%d" % i, ts)
                else:
                    eng.delete_if_newer(key.encode(), ts)
            return {k: v for k, v in eng.snapshot()}
        finally:
            eng.close()

    base_order = list(range(len(ops)))
    reference_state = run(base_order, restart_at=len(ops) // 2)
    for trial in range(8):
        order = base_order[:]
        random.Random(trial).shuffle(order)
        state = run(order, restart_at=random.Random(trial + 100).randrange(len(ops)))
        assert state == reference_state, f"order {order} diverged"


def test_non_utf8_key_replicates(pair, broker):
    """A key whose bytes are not valid UTF-8 must replicate end-to-end:
    surrogateescape decode (replicator._to_event), surrogateescape codec
    round-trip, and surrogateescape re-encode in the applier. Historically
    the strict encode raised and the transport guard ate the event."""
    n1, n2 = pair
    raw_key = b"bin\xff\xfekey"
    ev = ChangeEvent(
        op=OpKind.SET,
        key=raw_key.decode("utf-8", "surrogateescape"),
        val=b"binval",
        ts=time.time_ns(),
        src="rogue",
    )
    topic = n1.cluster._cfg.replication.topic_prefix + "/events"
    rogue = TcpTransport(broker.host, broker.port)
    try:
        rogue.publish(topic, encode_cbor(ev))
        assert wait_for(lambda: n2.engine.get(raw_key) == b"binval")
        assert wait_for(lambda: n1.engine.get(raw_key) == b"binval")
        # The event must have been applied, not swallowed by the callback
        # guard (the pre-fix failure mode).
        assert n2.cluster.replicator._transport.callback_errors == 0
    finally:
        rogue.close()


def test_equal_ts_cross_writer_converges_without_sync():
    """Two replicas apply the same pair of equal-ts events from different
    writers in OPPOSITE orders. The engine's digest tie-break (set_if_newer)
    must land both on the same value — replication alone converges, no
    anti-entropy needed (historically the applier's in-memory op_id
    tie-break made this order-dependent after a restart)."""
    from merklekv_tpu.cluster.applier import LWWApplier
    from merklekv_tpu.native_bindings import NativeEngine

    ts = time.time_ns()
    ev_a = ChangeEvent(op=OpKind.SET, key="eq", val=b"alpha", ts=ts,
                       src="w1", op_id=b"\x01" * 16)
    ev_b = ChangeEvent(op=OpKind.SET, key="eq", val=b"beta", ts=ts,
                       src="w2", op_id=b"\x02" * 16)

    def engine_applier(engine):
        return LWWApplier(
            engine.set,
            lambda k: engine.delete(k),
            set_ts_fn=lambda k, v, t: engine.set_if_newer(k, v, t),
            del_ts_fn=lambda k, t: engine.delete_if_newer(k, t),
            store_ts_fn=lambda k: max(
                engine.get_ts(k) or 0, engine.tombstone_ts(k) or 0
            ),
        )

    e1, e2 = NativeEngine("mem"), NativeEngine("mem")
    try:
        a1, a2 = engine_applier(e1), engine_applier(e2)
        a1.apply(ev_a)
        a1.apply(ev_b)
        a2.apply(ev_b)
        a2.apply(ev_a)
        assert e1.get(b"eq") == e2.get(b"eq")
        assert e1.get(b"eq") in (b"alpha", b"beta")
    finally:
        e1.close()
        e2.close()


class LossyTransport:
    """Transport wrapper dropping a deterministic fraction of publishes —
    frame loss on the QoS-0 fabric (VERDICT r4 item 10)."""

    def __init__(self, inner, drop_rate: float, seed: int = 7):
        import random

        self._inner = inner
        self._rng = random.Random(seed)
        self._rate = drop_rate
        self.dropped = 0
        self.passed = 0

    def publish(self, topic, payload):
        if self._rng.random() < self._rate:
            self.dropped += 1
            return  # frame lost in transit
        self.passed += 1
        self._inner.publish(topic, payload)

    def subscribe(self, prefix, cb):
        self._inner.subscribe(prefix, cb)

    def unsubscribe(self, cb):
        self._inner.unsubscribe(cb)

    def close(self):
        self._inner.close()


@pytest.mark.integration
def test_convergence_under_frame_loss(broker):
    """QoS-0 replication + periodic anti-entropy converge under heavy frame
    loss — the design argument behind dropping the reference's QoS-1
    (replication.rs:257-264) becomes a measured number. 40% of publishes
    are dropped; the anti-entropy loop (200 ms interval) must repair every
    hole. The reference's own budget for LOSSLESS propagation through a
    public broker is 3-5 s (README.md:56)."""
    from merklekv_tpu.cluster.transport import TcpTransport

    topic = f"loss-{uuid.uuid4().hex[:8]}"

    def make_node(node_id, peers):
        engine = NativeEngine("mem")
        server = NativeServer(engine, "127.0.0.1", 0)
        server.start()
        cfg = Config()
        cfg.replication.enabled = True
        cfg.replication.mqtt_broker = broker.host
        cfg.replication.mqtt_port = broker.port
        cfg.replication.topic_prefix = topic
        cfg.replication.client_id = node_id
        cfg.anti_entropy.enabled = True
        cfg.anti_entropy.interval_seconds = 0.2
        cfg.anti_entropy.peers = peers
        lossy = LossyTransport(
            TcpTransport(broker.host, broker.port), drop_rate=0.4
        )
        node = ClusterNode(cfg, engine, server, transport=lossy)
        node.start()
        client = MerkleKVClient("127.0.0.1", server.port, timeout=15).connect()
        return engine, server, node, client, lossy

    e1, s1, n1, c1, t1 = make_node("loss-1", [])
    # Node 2 periodically syncs FROM node 1 (the anti-entropy backstop).
    e2, s2, n2, c2, t2 = make_node("loss-2", [f"127.0.0.1:{s1.port}"])
    try:
        n_keys = 60
        t0 = time.time()
        for i in range(n_keys):
            c1.set(f"loss{i:03d}", f"v{i}")
        c1.delete("loss000")  # a deletion must survive loss too

        def converged():
            return c1.hash() == c2.hash()

        assert wait_for(converged, timeout=30), (
            f"no convergence: dropped={t1.dropped} passed={t1.passed}"
        )
        seconds = time.time() - t0
        # The point of the test: real loss happened AND we converged.
        assert t1.dropped > 0, "drop injector never fired"
        assert c2.get("loss001") == "v1"
        assert c2.get("loss000") is None
        # Report the number (visible with -s / in CI logs).
        print(
            f"\nconverged in {seconds:.2f}s with "
            f"{t1.dropped}/{t1.dropped + t1.passed} frames dropped"
        )
    finally:
        for cl, nd, sv, en in ((c1, n1, s1, e1), (c2, n2, s2, e2)):
            cl.close()
            nd.stop()
            sv.close()
            en.close()


def test_framed_transport_reconnects_after_broker_restart():
    """Broker restart heals the fabric without node restarts: the transport
    re-dials with backoff and events flow again (the reference's rumqttc
    behavior, replication.rs:148-166)."""
    broker = TcpBroker()
    port = broker.port
    t_pub = TcpTransport(broker.host, port)
    t_sub = TcpTransport(broker.host, port)
    got = []
    try:
        t_sub.subscribe("rc/events", lambda topic, p: got.append(p))
        time.sleep(0.05)
        t_pub.publish("rc/events", b"before")
        deadline = time.time() + 5
        while time.time() < deadline and got != [b"before"]:
            time.sleep(0.01)
        assert got == [b"before"]

        broker.close()
        # Same port: restarted broker, new process in production terms.
        deadline = time.time() + 10
        broker = None
        while time.time() < deadline and broker is None:
            try:
                broker = TcpBroker(port=port)
            except OSError:
                time.sleep(0.1)  # TIME_WAIT on the listener
        assert broker is not None, "broker could not rebind its port"
        deadline = time.time() + 15
        while time.time() < deadline and (
            t_pub.reconnects < 1 or t_sub.reconnects < 1
        ):
            time.sleep(0.05)
        assert t_pub.reconnects >= 1 and t_sub.reconnects >= 1
        from merklekv_tpu.utils.tracing import get_metrics

        assert get_metrics().snapshot()["counters"].get(
            "transport.reconnects", 0
        ) >= 2

        deadline = time.time() + 10
        while time.time() < deadline and b"after" not in got:
            t_pub.publish("rc/events", b"after")
            time.sleep(0.1)
        assert b"after" in got
    finally:
        t_pub.close()
        t_sub.close()
        if broker is not None:
            broker.close()


def test_framed_outbox_flushes_after_heal():
    """Events published WHILE the broker is down are buffered (bounded
    outbox) and delivered after the link heals — replication survives an
    outage instead of silently dropping every write in the window."""
    broker = TcpBroker()
    port = broker.port
    t_pub = TcpTransport(broker.host, port)
    t_sub = TcpTransport(broker.host, port)
    # The publisher's post-heal drain must not beat the subscriber's
    # reconnect (the broker fans only to CONNECTED clients); stagger the
    # publisher's first retry so the subscriber deterministically wins.
    t_pub._BACKOFF_FIRST = 1.5
    got = []
    try:
        t_sub.subscribe("ob/events", lambda topic, p: got.append(p))
        time.sleep(0.05)
        broker.close()
        # Wait for the DETECTED-down state: events sent into the kernel
        # buffer of a dead-but-undetected link are inherently lossy
        # without broker acks; the outbox guarantee starts at detection.
        deadline = time.time() + 5
        while time.time() < deadline and not (
            t_pub.link_down and t_sub.link_down
        ):
            time.sleep(0.02)
        assert t_pub.link_down and t_sub.link_down
        for i in range(5):
            t_pub.publish("ob/events", b"during-%d" % i)
        # Nothing could have been delivered: the broker is down.
        assert got == []
        deadline = time.time() + 10
        broker = None
        while time.time() < deadline and broker is None:
            try:
                broker = TcpBroker(port=port)
            except OSError:
                time.sleep(0.1)
        assert broker is not None, "broker could not rebind its port"
        deadline = time.time() + 15
        while time.time() < deadline and len(got) < 5:
            time.sleep(0.05)
        assert got == [b"during-%d" % i for i in range(5)], got
    finally:
        t_pub.close()
        t_sub.close()
        if broker is not None:
            broker.close()


def test_outbox_overflow_drops_oldest_and_counts():
    """The outage buffer is bounded: overflow drops the OLDEST event (LWW:
    newer state supersedes older) and counts the drop."""
    from merklekv_tpu.cluster import transport as tmod

    broker = TcpBroker()
    t = TcpTransport(broker.host, broker.port)
    try:
        t.link_down = True  # force the enqueue path; no wire traffic
        n_extra = 7
        for i in range(tmod.OUTBOX_LIMIT + n_extra):
            t.publish("of/events", b"e-%d" % i)
        assert len(t._outbox) == tmod.OUTBOX_LIMIT
        assert t.outbox_dropped == n_extra
        # Oldest dropped: the queue starts at e-<n_extra>.
        assert t._outbox[0] == ("of/events", b"e-%d" % n_extra)
        assert t._outbox[-1] == (
            "of/events", b"e-%d" % (tmod.OUTBOX_LIMIT + n_extra - 1)
        )
    finally:
        t.link_down = False
        t.close()
        broker.close()

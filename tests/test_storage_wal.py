"""WAL framing: roundtrip, rotation, fsync accounting, torn-tail handling.

The torn-tail sweep is the satellite the ISSUE names: truncate the final
frame at EVERY byte offset (testing/faults.py truncate_file) and assert the
scan stops cleanly at the last whole record — never raises, never yields a
partial record, never misclassifies the tear as interior corruption.
"""

import os
import shutil

import pytest

from merklekv_tpu.storage import wal
from merklekv_tpu.testing.faults import corrupt_file, truncate_file


def _records(n, op_every_del=5):
    recs = []
    for i in range(n):
        if i % op_every_del == op_every_del - 1:
            recs.append(wal.WalRecord(wal.OP_DEL, b"key%03d" % i, None, 1000 + i))
        else:
            recs.append(
                wal.WalRecord(wal.OP_SET, b"key%03d" % i, b"value-%d" % i, 1000 + i)
            )
    return recs


def _write_segment(directory, recs, **kw):
    w = wal.WalWriter(directory, 0, fsync_policy="never", **kw)
    for r in recs:
        w.append(r)
    w.close()
    return wal.segment_path(directory, 0)


def test_roundtrip_all_ops(tmp_path):
    recs = _records(20) + [wal.WalRecord(wal.OP_TRUNCATE, b"", None, 9999)]
    path = _write_segment(str(tmp_path), recs)
    scan = wal.scan_segment(path)
    assert scan.clean
    assert scan.records == recs
    assert scan.good_offset == os.path.getsize(path)


def test_empty_values_and_binary_keys(tmp_path):
    recs = [
        wal.WalRecord(wal.OP_SET, b"\x00\xffbin", b"", 1),
        wal.WalRecord(wal.OP_SET, b"", b"\x00" * 100, 2),
        wal.WalRecord(wal.OP_DEL, b"\xff" * 40, None, 3),
    ]
    path = _write_segment(str(tmp_path), recs)
    scan = wal.scan_segment(path)
    assert scan.clean and scan.records == recs


def test_torn_tail_every_byte_offset(tmp_path):
    """Truncate at every byte: scan yields exactly the whole frames that
    fit, flags the tear, and never raises."""
    recs = _records(8)
    src = _write_segment(str(tmp_path / "src"), recs)
    # Frame end offsets, starting after the segment magic.
    ends = [len(wal.SEGMENT_MAGIC)]
    for r in recs:
        ends.append(ends[-1] + len(wal.encode_frame(r)))
    total = os.path.getsize(src)
    assert ends[-1] == total

    work = tmp_path / "work"
    work.mkdir()
    dst = str(work / os.path.basename(src))
    for cut in range(total + 1):
        shutil.copyfile(src, dst)
        truncate_file(dst, cut)
        scan = wal.scan_segment(dst)
        n_whole = sum(1 for e in ends[1:] if e <= cut)
        assert len(scan.records) == n_whole, (cut, len(scan.records), n_whole)
        assert scan.records == recs[:n_whole]
        if cut < len(wal.SEGMENT_MAGIC):
            assert not scan.clean
        elif cut in ends:
            assert scan.clean, (cut, scan.error)
        else:
            assert not scan.clean
            assert scan.torn, (cut, scan.error)
            assert scan.good_offset == ends[n_whole]


def test_interior_corruption_is_not_torn(tmp_path):
    recs = _records(10)
    path = _write_segment(str(tmp_path), recs)
    # Flip a payload byte of frame 3 (well before EOF).
    ends = [len(wal.SEGMENT_MAGIC)]
    for r in recs:
        ends.append(ends[-1] + len(wal.encode_frame(r)))
    corrupt_file(path, ends[3] + 12)
    scan = wal.scan_segment(path)
    assert not scan.clean
    assert not scan.torn  # full frame present, CRC failed, more data behind
    assert scan.records == recs[:3]
    assert scan.good_offset == ends[3]


def test_corrupt_tail_frame_counts_as_torn(tmp_path):
    """Bit-flip inside the FINAL frame: indistinguishable from a torn
    write at scan level, so it reports torn (recovery cuts it)."""
    recs = _records(4)
    path = _write_segment(str(tmp_path), recs)
    corrupt_file(path, os.path.getsize(path) - 2)
    scan = wal.scan_segment(path)
    assert not scan.clean and scan.torn
    assert scan.records == recs[:3]


def test_bad_magic_is_corruption(tmp_path):
    recs = _records(3)
    path = _write_segment(str(tmp_path), recs)
    corrupt_file(path, 0)
    scan = wal.scan_segment(path)
    assert not scan.clean and not scan.torn and scan.records == []


def test_rotation_and_listing(tmp_path):
    w = wal.WalWriter(str(tmp_path), 0, fsync_policy="never", segment_bytes=256)
    for r in _records(50):
        w.append(r)
    w.close()
    segs = wal.list_segments(str(tmp_path))
    assert len(segs) > 1
    assert [s for s, _ in segs] == list(range(len(segs)))
    assert w.rotations == len(segs) - 1
    # Every record survives, in order, across the segment boundary.
    got = []
    for _, path in segs:
        scan = wal.scan_segment(path)
        assert scan.clean
        got.extend(scan.records)
    assert got == _records(50)


def test_fsync_policies(tmp_path):
    recs = _records(10)
    w = wal.WalWriter(str(tmp_path / "a"), 0, fsync_policy="always")
    for r in recs:
        w.append(r)
    assert w.fsyncs >= 10
    w.close()

    w = wal.WalWriter(str(tmp_path / "b"), 0, fsync_policy="interval")
    for r in recs:
        w.append(r)
    n0 = w.fsyncs
    assert w.fsync() is True  # dirty -> flushed
    assert w.fsync() is False  # clean -> no-op
    assert w.fsyncs == n0 + 1
    w.close()

    with pytest.raises(ValueError):
        wal.WalWriter(str(tmp_path / "c"), 0, fsync_policy="bogus")


def test_append_many_batches(tmp_path):
    w = wal.WalWriter(str(tmp_path), 0, fsync_policy="always")
    assert w.append_many(_records(25)) == 25
    assert w.fsyncs == 1  # one fsync covers the whole drained batch
    w.close()
    scan = wal.scan_segment(wal.segment_path(str(tmp_path), 0))
    assert scan.clean and len(scan.records) == 25


def test_append_many_single_write_across_rotation(tmp_path):
    """The grouped append (one write() per segment stretch) must keep
    every frame intact across forced segment rotations: the full record
    stream survives, in order, split over clean segments."""
    recs = _records(40)
    w = wal.WalWriter(
        str(tmp_path), 0, fsync_policy="never", segment_bytes=256
    )
    assert w.append_many(recs) == 40
    assert w.rotations >= 2  # the batch genuinely crossed segments
    w.close()
    replayed = []
    for _seq, path in wal.list_segments(str(tmp_path)):
        scan = wal.scan_segment(path)
        assert scan.clean, scan.error
        replayed.extend(scan.records)
    assert replayed == recs


def test_reopen_with_start_offset_cuts_torn_tail(tmp_path):
    recs = _records(5)
    path = _write_segment(str(tmp_path), recs)
    truncate_file(path, os.path.getsize(path) - 3)  # tear the last frame
    scan = wal.scan_segment(path)
    assert scan.torn and len(scan.records) == 4
    w = wal.WalWriter(
        str(tmp_path), 0, fsync_policy="never", start_offset=scan.good_offset
    )
    w.append(wal.WalRecord(wal.OP_SET, b"after", b"tear", 5000))
    w.close()
    scan2 = wal.scan_segment(path)
    assert scan2.clean
    assert scan2.records == recs[:4] + [
        wal.WalRecord(wal.OP_SET, b"after", b"tear", 5000)
    ]

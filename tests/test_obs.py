"""Observability plane (merklekv_tpu/obs/): histogram bucket math,
callback gauges, Prometheus exporter scrape-format validation,
METRICS/STATS parity across clients, correlated TRACE cycles, the
span total_us fix, and the `top` dashboard renderer."""

import asyncio
import json
import math
import re
import time
import urllib.request

import pytest

from merklekv_tpu.client import AsyncMerkleKVClient, MerkleKVClient
from merklekv_tpu.cluster.node import ClusterNode
from merklekv_tpu.config import Config
from merklekv_tpu.native_bindings import NativeEngine, NativeServer
from merklekv_tpu.obs.exporter import render_prometheus
from merklekv_tpu.obs.metrics import (
    BUCKET_BOUNDS,
    Histogram,
    Metrics,
    bucket_index,
)
from merklekv_tpu.obs.trace import CycleTrace, PeerTrace, SyncTraceBuffer
from merklekv_tpu.utils.tracing import get_metrics, span


@pytest.fixture
def server():
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0)
    srv.start()
    yield eng, srv
    srv.close()
    eng.close()


@pytest.fixture
def cluster_node(server):
    """A ClusterNode with an ephemeral-port exporter attached."""
    eng, srv = server
    cfg = Config()
    cfg.observability.http_port = -1  # ephemeral
    cfg.anti_entropy.engine = "cpu"
    node = ClusterNode(cfg, eng, srv)
    node.start()
    yield eng, srv, node
    node.stop()


# --------------------------------------------------------- histogram math

def test_bucket_bounds_are_log2_from_1us():
    assert BUCKET_BOUNDS[0] == 1e-6
    for lo, hi in zip(BUCKET_BOUNDS, BUCKET_BOUNDS[1:]):
        assert hi == lo * 2


def test_bucket_index_golden():
    # (observation seconds, expected bucket index) — le semantics: the
    # first bound >= the value wins; over the top bound = overflow slot.
    golden = [
        (0.0, 0),
        (5e-7, 0),
        (1e-6, 0),
        (1.0001e-6, 1),
        (2e-6, 1),
        (3e-6, 2),
        (4e-6, 2),
        (1e-3, 10),       # 1024 us bound
        (0.5, 19),        # 0.524288 s bound
        (BUCKET_BOUNDS[-1], len(BUCKET_BOUNDS) - 1),
        (BUCKET_BOUNDS[-1] * 2, len(BUCKET_BOUNDS)),  # +Inf overflow
    ]
    for value, want in golden:
        assert bucket_index(value) == want, (value, bucket_index(value), want)


def test_bucket_index_exact_bounds_never_spill():
    for i, bound in enumerate(BUCKET_BOUNDS):
        assert bucket_index(bound) == i


def test_histogram_quantiles_and_cumulative():
    h = Histogram()
    for _ in range(99):
        h.observe(10e-6)  # -> le 1.6e-05 bucket
    h.observe(1.0)        # one slow outlier -> le 1.048576
    snap = h.snapshot()
    assert snap["count"] == 100
    assert snap["max"] == 1.0
    assert abs(snap["sum"] - (99 * 10e-6 + 1.0)) < 1e-9
    # p50/p90 sit in the 16us bucket; p99 still below the outlier; max/p100
    # reaches the outlier's bucket bound.
    assert h.quantile(0.5) == pytest.approx(1.6e-5)
    assert h.quantile(0.9) == pytest.approx(1.6e-5)
    assert h.quantile(0.99) == pytest.approx(1.6e-5)
    assert h.quantile(1.0) == pytest.approx(1.048576)
    # Cumulative view is monotone and ends at (inf, count).
    cum = h.cumulative()
    assert cum[-1] == (math.inf, 100)
    counts = [c for _, c in cum]
    assert counts == sorted(counts)


def test_histogram_empty_quantile_is_none():
    assert Histogram().quantile(0.5) is None


def test_overflow_quantile_reports_observed_max():
    h = Histogram()
    h.observe(100.0)  # beyond the last bound
    assert h.quantile(0.5) == 100.0


# --------------------------------------------------------------- gauges

def test_gauges_register_snapshot_unregister():
    m = Metrics()
    m.register_gauge("g.num", lambda: 7, help="seven")
    m.register_gauge("g.map", lambda: {"a": 1.5}, label="peer")
    m.register_gauge("g.boom", lambda: 1 / 0)
    snap = m.gauges_snapshot()
    assert snap["g.num"]["value"] == 7
    assert snap["g.map"]["value"] == {"a": 1.5}
    assert snap["g.map"]["label"] == "peer"
    assert "g.boom" not in snap  # failing callback drops ITS gauge only
    m.unregister_gauge("g.num")
    assert "g.num" not in m.gauges_snapshot()


def test_unregister_gauge_is_identity_checked():
    """A stopped node must not strip a successor's same-named gauge
    (registration is last-wins across nodes in one process)."""
    m = Metrics()
    fn_a, fn_b = (lambda: 1), (lambda: 2)
    m.register_gauge("g", fn_a)
    m.register_gauge("g", fn_b)  # node B replaces node A
    m.unregister_gauge("g", fn_a)  # node A stops: B's registration survives
    assert m.gauges_snapshot()["g"]["value"] == 2
    m.unregister_gauge("g", fn_b)
    assert "g" not in m.gauges_snapshot()


def test_reset_clears_series_but_keeps_gauges():
    m = Metrics()
    m.inc("c", 3)
    m.observe("h", 0.001)
    m.register_gauge("g", lambda: 1)
    m.reset()
    snap = m.snapshot()
    assert snap["counters"] == {} and snap["histograms"] == {}
    assert "g" in m.gauges_snapshot()  # live callbacks survive reset


# ------------------------------------------------- exporter text format

# Prometheus text-format grammar (v0.0.4): comment/TYPE/HELP lines, or
# `name{label="value",...} value [timestamp]` samples.
_PROM_COMMENT = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+$")
_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\.)*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\.)*\")*\})?"
    r" (?:[-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|\+Inf|-Inf|NaN)"
    r"(?: [0-9]+)?$"
)


def _assert_prometheus_grammar(body: str) -> None:
    for line in body.splitlines():
        if not line:
            continue
        assert _PROM_COMMENT.match(line) or _PROM_SAMPLE.match(line), (
            f"line fails Prometheus text grammar: {line!r}"
        )


def test_render_prometheus_grammar_full_surface():
    m = Metrics()
    m.inc("anti_entropy.syncs", 2)
    m.observe_span("anti_entropy.sync_once", 0.01)
    m.observe("storage.wal_fsync", 0.0005)
    m.register_gauge("keyspace.keys", lambda: 42, help="Live keys.")
    m.register_gauge(
        "peer.state", lambda: {"127.0.0.1:7379": 2}, label="peer"
    )
    stats_text = (
        "STATS\r\nset_commands:4\r\nuptime:0d 0h 0m 1s\r\n"
        "cmd_latency_us_le_1:2\r\ncmd_latency_us_le_2:1\r\n"
        "cmd_latency_us_le_inf:0\r\ncmd_latency_us_sum:5\r\n"
        "cmd_latency_us_count:3\r\nEND\r\n"
    )
    body = render_prometheus(m, stats_text)
    _assert_prometheus_grammar(body)
    assert "mkv_anti_entropy_syncs_total 2" in body
    assert 'mkv_span_duration_seconds_bucket{span="anti_entropy.sync_once"' \
        in body
    assert "mkv_storage_wal_fsync_seconds_count 1" in body
    assert "mkv_keyspace_keys 42" in body
    assert 'mkv_peer_state{peer="127.0.0.1:7379"} 2' in body
    assert "mkv_native_set_commands 4" in body
    # The native latency buckets fold into one cumulative histogram.
    assert 'mkv_native_cmd_latency_seconds_bucket{le="2e-06"} 3' in body
    assert "mkv_native_cmd_latency_seconds_count 3" in body
    # Human-readable native lines are skipped, not mangled.
    assert "uptime:0d" not in body


def test_size_histogram_renders_unitless_family():
    """observe_size histograms (replication batch sizes) share the log2
    bucket machinery but render as a unitless family — bounds in UNITS
    (2^i events), no `_seconds` suffix, sum rescaled back to units."""
    m = Metrics()
    m.observe_size("replicator.batch_size", 7)
    m.observe_size("replicator.batch_size", 300)
    body = render_prometheus(m)
    _assert_prometheus_grammar(body)
    assert "mkv_replicator_batch_size_seconds" not in body
    assert 'mkv_replicator_batch_size_bucket{le="8"} 1' in body
    assert 'mkv_replicator_batch_size_bucket{le="512"} 2' in body
    assert "mkv_replicator_batch_size_count 2" in body
    assert "mkv_replicator_batch_size_sum 307" in body


def test_exporter_endpoint_two_node_cluster(cluster_node):
    """Acceptance shape: a 2-node cluster under write + anti-entropy load
    serves a Prometheus-parseable /metrics page with histogram series, a
    gauge, and bridged native counters; TRACE 5 attributes the cycles."""
    eng_b, srv_b, node = cluster_node
    eng_a = NativeEngine("mem")
    srv_a = NativeServer(eng_a, "127.0.0.1", 0)
    srv_a.start()
    try:
        for i in range(64):
            eng_a.set(b"obs:%04d" % i, b"v%d" % i)
        with MerkleKVClient("127.0.0.1", srv_b.port) as c:
            for i in range(16):
                c.set(f"local:{i:03d}", f"w{i}")
            assert c.sync_with("127.0.0.1", srv_a.port)
            assert c.sync_with("127.0.0.1", srv_a.port)  # converged: noop
            rows = c.trace(5)
        assert rows, "TRACE returned no cycles"
        newest = rows[0]
        for field in ("cycle", "peer", "mode", "outcome", "bytes_sent",
                      "bytes_received", "rounds", "repairs"):
            assert field in newest, f"TRACE row missing {field}"
        assert newest["peer"] == f"127.0.0.1:{srv_a.port}"
        assert newest["outcome"] == "noop"
        repaired = next(r for r in rows if r["outcome"] == "ok")
        assert int(repaired["repairs"]) >= 64
        assert int(repaired["bytes_received"]) > 0

        port = node.metrics_port
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ).read().decode()
        _assert_prometheus_grammar(body)
        # At least one histogram with _bucket/_sum/_count series.
        assert 'mkv_span_duration_seconds_bucket{span="anti_entropy.' in body
        assert "mkv_span_duration_seconds_sum" in body
        assert "mkv_span_duration_seconds_count" in body
        # A gauge over live node state.
        key_line = next(
            ln for ln in body.splitlines()
            if ln.startswith("mkv_keyspace_keys ")
        )
        assert float(key_line.split()[1]) == eng_b.dbsize()
        # Native STATS bridged into the same namespace.
        assert "mkv_native_set_commands" in body
        assert "mkv_native_cmd_latency_seconds_bucket" in body

        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5
        ) as resp:
            assert resp.status == 200
            payload = json.loads(resp.read().decode())
        assert payload["status"] == "ok"
        assert payload["keys"] == eng_b.dbsize()
    finally:
        srv_a.close()
        eng_a.close()


def test_exporter_404_on_unknown_path(cluster_node):
    _, _, node = cluster_node
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(
            f"http://127.0.0.1:{node.metrics_port}/nope", timeout=5
        )
    assert exc.value.code == 404


# --------------------------------------------- METRICS / STATS parity

def test_metrics_native_only_node_serves_empty_block(server):
    """Without a cluster plane METRICS is an empty block on BOTH clients
    (native default), and STATS parses identically too."""
    _, srv = server

    with MerkleKVClient("127.0.0.1", srv.port) as c:
        assert c.metrics() == {}
        sync_stats = c.stats()

    async def go():
        async with AsyncMerkleKVClient("127.0.0.1", srv.port) as ac:
            return await ac.metrics(), await ac.stats()

    async_metrics, async_stats = asyncio.run(go())
    assert async_metrics == {}
    assert set(async_stats) == set(sync_stats)


def test_metrics_parity_sync_async_cluster_attached(cluster_node):
    """Cluster-attached node serves control-plane counters; the sync and
    async clients parse the identical block (sentinel counter equality)."""
    _, srv, _node = cluster_node
    get_metrics().inc("obs_parity.sentinel", 41)

    with MerkleKVClient("127.0.0.1", srv.port) as c:
        sync_m = c.metrics()

    async def go():
        async with AsyncMerkleKVClient("127.0.0.1", srv.port) as ac:
            return await ac.metrics()

    async_m = asyncio.run(go())
    assert sync_m.get("obs_parity.sentinel") == "41"
    assert async_m.get("obs_parity.sentinel") == "41"
    assert set(sync_m) == set(async_m)


def test_span_total_us_not_truncated(cluster_node):
    """Sub-millisecond spans used to report total_ms 0; the canonical total
    is microseconds, and the deprecated total_ms field is GONE from the
    wire after its one-release window (PROTOCOL.md "METRICS")."""
    _, srv, _node = cluster_node
    get_metrics().reset()
    # Deterministic sub-ms observation (a sleep-based span can overshoot
    # 1 ms under CI load and void the truncation assertion).
    get_metrics().observe_span("obs_tiny.op", 0.0003)
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        m = c.metrics()
    assert int(m["span.obs_tiny.op.total_us"]) > 0
    assert "span.obs_tiny.op.total_ms" not in m  # deprecation window over
    assert int(m["span.obs_tiny.op.p50_us"]) > 0


# ----------------------------------------------------------- TRACE ring

def test_trace_verb_without_cluster_plane(server):
    _, srv = server
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        assert c.trace(5) == []  # native default: empty table


def test_trace_ring_buffer_capacity_and_order():
    buf = SyncTraceBuffer(capacity=3)
    for i in range(1, 6):
        buf.append(CycleTrace(cycle_id=i, kind="pairwise",
                              peers=[PeerTrace(peer="p:1")]))
    assert len(buf) == 3
    assert [c.cycle_id for c in buf.last(10)] == [5, 4, 3]  # newest first
    wire = buf.wire_dump(2)
    assert wire.startswith("TRACES 2\r\n") and wire.endswith("END\r\n")
    assert "cycle=5" in wire and "cycle=3" not in wire


def test_trace_records_error_outcome(server):
    """A cycle against a dead peer lands in the ring buffer as an error."""
    from merklekv_tpu.cluster.sync import SyncManager
    from merklekv_tpu.obs.trace import get_trace_buffer

    eng, srv = server
    dead = NativeServer(eng, "127.0.0.1", 0)
    dead.start()
    port = dead.port
    dead.close()
    mgr = SyncManager(eng, device="cpu")
    before = len(get_trace_buffer())
    with pytest.raises(Exception):
        mgr.sync_once("127.0.0.1", port)
    cycles = get_trace_buffer().last(len(get_trace_buffer()) - before + 1)
    mine = next(c for c in cycles if c.peers
                and c.peers[0].peer == f"127.0.0.1:{port}")
    assert mine.peers[0].outcome == "error"
    assert mine.peers[0].error


def test_cycle_id_stamped_into_spans(server, caplog):
    import logging

    from merklekv_tpu.cluster.sync import SyncManager

    eng, srv = server
    eng.set(b"c", b"v")
    local = NativeEngine("mem")
    try:
        with caplog.at_level(logging.INFO, logger="merklekv"):
            SyncManager(local, device="cpu").sync_once(
                "127.0.0.1", srv.port
            )
        spans = [json.loads(r.message) for r in caplog.records
                 if r.message.startswith("{")]
        cycle_spans = [s for s in spans
                       if s.get("span") == "anti_entropy.sync_once"]
        assert cycle_spans and all("cycle" in s for s in cycle_spans)
    finally:
        local.close()


# ----------------------------------------------------------------- top

def test_top_sample_and_render(server):
    from merklekv_tpu.obs import top as topmod

    eng, srv = server
    node = f"127.0.0.1:{srv.port}"
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        for i in range(10):
            c.set(f"t:{i}", "v")
    s0 = topmod.sample_node(node)
    assert s0.ok and s0.keys == 10
    assert s0.latency_p50_us is not None and s0.latency_p50_us > 0
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        for i in range(5):
            c.get(f"t:{i}")
    time.sleep(0.05)
    s1 = topmod.sample_node(node)
    frame = topmod.render_table({node: s0}, {node: s1})
    assert node in frame and "UP" in frame and "KEYS" in frame
    # A dead node renders a DOWN row instead of raising.
    dead = "127.0.0.1:1"
    s_dead = topmod.sample_node(dead, timeout=0.2)
    frame2 = topmod.render_table({}, {dead: s_dead})
    assert "DOWN" in frame2


def test_top_once_cli(server):
    from merklekv_tpu.obs.top import main as top_main

    _, srv = server
    import io
    import contextlib

    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = top_main([
            "--nodes", f"127.0.0.1:{srv.port}", "--interval", "0.1",
            "--once",
        ])
    assert rc == 0
    assert f"127.0.0.1:{srv.port}" in out.getvalue()


# -------------------------------------------- metadata catalog (ISSUE 7)

def test_catalog_and_observability_doc_stay_in_sync():
    """Every cataloged family must be discoverable from
    docs/OBSERVABILITY.md — either by its literal registry name or via a
    documented `<subsystem>.*` wildcard (the counters paragraph documents
    whole subsystems that way). A new catalog entry without a doc home
    fails here."""
    import os

    from merklekv_tpu.obs.catalog import CATALOG

    doc = open(
        os.path.join(os.path.dirname(__file__), "..", "docs",
                     "OBSERVABILITY.md")
    ).read()
    missing = []
    for name in CATALOG:
        subsystem = name.split(".")[0]
        if name in doc or f"{subsystem}.*" in doc or f"mkv_{name}" in doc:
            continue
        # Exporter-built families live under their sanitized mkv_ name.
        if f"mkv_{name.replace('.', '_')}" in doc:
            continue
        missing.append(name)
    assert not missing, f"catalog entries undocumented: {missing}"


def test_scrape_every_family_has_help_and_type(cluster_node):
    """Every family on a live scrape (registry counters/histograms/gauges
    AND the bridged native STATS block) carries # HELP and # TYPE."""
    eng, srv, node = cluster_node
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        for i in range(5):
            c.set(f"ht:{i}", "v")
    get_metrics().inc("some.uncataloged_counter")  # fallback path too
    get_metrics().observe("some.uncataloged_latency", 0.001)
    with urllib.request.urlopen(
        f"http://127.0.0.1:{node.metrics_port}/metrics", timeout=5
    ) as r:
        page = r.read().decode()
    helped, typed, families = set(), set(), set()
    for line in page.splitlines():
        if line.startswith("# HELP "):
            helped.add(line.split(" ", 3)[2])
        elif line.startswith("# TYPE "):
            typed.add(line.split(" ", 3)[2])
        elif line.startswith("mkv_"):
            name = line.split("{", 1)[0].split(" ", 1)[0]
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix):
                    name = name[: -len(suffix)]
                    break
            families.add(name)
    bare = {f for f in families if f not in typed or f not in helped}
    assert not bare, f"families scraped without HELP/TYPE: {sorted(bare)}"
    # The uncataloged counter got the generated fallback text.
    assert "Uncataloged counter some.uncataloged_counter" in page


def test_profile_verb_starts_bounded_capture(cluster_node):
    """PROFILE <secs> answers a capture directory immediately; a second
    capture while one runs is refused; a bare native node errors."""
    import os

    eng, srv, node = cluster_node
    # Generous timeout: the first capture initializes the jax backend
    # inside the serving callback, which can take seconds on a cold CI.
    with MerkleKVClient("127.0.0.1", srv.port, timeout=60.0) as c:
        logdir = c.profile(1)
        assert os.path.isdir(logdir)
        with pytest.raises(Exception) as exc:
            c.profile(1)
        assert "already running" in str(exc.value)
        # Parser bounds.
        with pytest.raises(Exception):
            c.profile(0)
    # The capture stops itself; wait so later tests can profile again.
    # Generous: stop_trace serializes the capture, and in a jax-heavy
    # process (the full suite has run thousands of programs by now) that
    # serialization alone takes 10s+.
    deadline = time.time() + 120
    while node._profiling and time.time() < deadline:
        time.sleep(0.1)
    assert not node._profiling
    # Capture artifacts actually landed (jax writes into <dir>/plugins).
    assert any(True for _ in os.scandir(logdir))


def test_profile_without_cluster_plane_errors(server):
    _, srv = server
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        with pytest.raises(Exception) as exc:
            c.profile(1)
        assert "unavailable" in str(exc.value)

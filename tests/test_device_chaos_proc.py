"""Device chaos through a REAL node process (the CI device-chaos step).

A spawned ``python -m merklekv_tpu`` server on the 8-way host-platform
mesh, with a persistent sharded-dispatch failure injected via the
``MKV_DEVICE_FAULTS`` env hook (the process-level seam the guard reads in
spawned processes): the node must come up, stay live, land the serving
tree on the surviving single-device rung, and answer HASH bit-identically
to the independent CPU golden chain — the degradation ladder working
end-to-end through config, __main__, the native server, and the cluster
callback, not just in-process objects.
"""

import os
import socket
import subprocess
import sys
import time

import pytest

from merklekv_tpu.client import MerkleKVClient
from merklekv_tpu.merkle.cpu import MerkleTree

pytestmark = pytest.mark.integration

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wait_port(port, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=1).close()
            return
        except OSError:
            time.sleep(0.05)
    raise TimeoutError(f"port {port} never came up")


def test_node_survives_persistent_shard_failure(tmp_path):
    procs = []
    try:
        broker = subprocess.Popen(
            [sys.executable, "-m", "merklekv_tpu.broker", "--port", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=dict(os.environ, PYTHONPATH=REPO),
        )
        procs.append(broker)
        line = broker.stdout.readline()
        assert "listening on" in line, line
        broker_port = int(line.rsplit(":", 1)[1].split()[0])

        cfg = tmp_path / "chaos.toml"
        cfg.write_text(
            f"""
host = "127.0.0.1"
port = 0
engine = "mem"

[replication]
enabled = true
mqtt_broker = "127.0.0.1"
mqtt_port = {broker_port}
topic_prefix = "devchaos"
client_id = "chaos-node"

[device]
sharding = "8"
max_staleness_ms = 100
dispatch_deadline_ms = 120000
"""
        )
        node = subprocess.Popen(
            [sys.executable, "-m", "merklekv_tpu", "--config", str(cfg)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=dict(
                os.environ,
                PYTHONPATH=REPO,
                MERKLEKV_JAX_PLATFORM="cpu",
                # The chaos hook: every sharded dispatch in the spawned
                # process fails persistently (environment-shaped).
                MKV_DEVICE_FAULTS="fail:shard*",
            ),
        )
        procs.append(node)
        line = node.stdout.readline()
        assert "listening on" in line, line
        port = int(line.rsplit(":", 1)[1].split()[0])
        _wait_port(port)

        golden = MerkleTree()
        with MerkleKVClient("127.0.0.1", port, timeout=30.0) as c:
            for i in range(64):
                c.set(f"chaos:{i:03d}", f"v{i}")
                golden.insert(f"chaos:{i:03d}", f"v{i}")
            # Poll HASH until the mirror warms (riding the ladder down to
            # the single-device rung under the injected fault) and the
            # pump window closes. The node must answer EVERY poll — a
            # wedged or dead node fails here, which is the point.
            deadline = time.time() + 180
            level = None
            while time.time() < deadline:
                assert c.ping(), "node stopped answering under the fault"
                if c.hash() == golden.root_hex():
                    metrics = c.metrics()
                    level = int(metrics.get("device.backend_level", -99))
                    if level == 1:
                        break
                time.sleep(0.25)
            assert c.hash() == golden.root_hex(), (
                "HASH diverged from the CPU golden chain under the fault"
            )
            assert level == 1, (
                f"serving backend never landed on the surviving "
                f"single-device rung (backend_level={level})"
            )
            # Still live for normal traffic on the degraded rung.
            c.set("chaos:after", "x")
            golden.insert("chaos:after", "x")
            deadline = time.time() + 30
            while time.time() < deadline:
                if c.hash() == golden.root_hex():
                    break
                time.sleep(0.1)
            assert c.hash() == golden.root_hex()
        assert node.poll() is None, "node process died"
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()

"""Flight recorder & post-mortem forensics plane (ISSUE 10).

The always-on black box: the event ring + metric sampler + CRC-framed
spill (obs/flightrec.py), the native slow-command log behind the FLIGHT
verb, the subsystem hooks (degradation, peer health, sync cycles, storage
latches), the offline ``blackbox`` analyzer, and the chaos acceptance
paths — kill -9 under write load always leaves a parseable spill whose
tail names the final transitions, and the spill reader survives
truncation at every byte offset.
"""

import asyncio
import json
import os
import random
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

from merklekv_tpu.client import AsyncMerkleKVClient, MerkleKVClient
from merklekv_tpu.config import Config
from merklekv_tpu.native_bindings import NativeEngine, NativeServer
from merklekv_tpu.obs import flightrec
from merklekv_tpu.obs.blackbox import (
    find_anomalies,
    link_traces,
    load_docs,
    main as blackbox_main,
    merge_timeline,
)
from merklekv_tpu.obs.flightrec import (
    FlightRecorder,
    FlightSpiller,
    MetricSampler,
    Sample,
    read_spill,
    write_spill,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def server():
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0)
    srv.start()
    yield eng, srv
    srv.close()
    eng.close()


@pytest.fixture
def node(server):
    from merklekv_tpu.cluster.node import ClusterNode

    eng, srv = server
    cfg = Config()
    cfg.observability.slow_command_us = 1  # everything is "slow"
    n = ClusterNode(cfg, eng, srv)
    flightrec.get_recorder().clear()
    n.start()
    yield eng, srv, n
    n.stop()


# ------------------------------------------------------------- ring + wire

def test_ring_capacity_order_and_drops():
    r = FlightRecorder(capacity=16)
    for i in range(40):
        r.record("tick", i=i)
    evs = r.last(0)
    assert len(evs) == 16
    assert [e.fields["i"] for e in evs] == list(range(24, 40))
    assert r.dropped() == 24
    # seq is monotonic and survives the ring's eviction
    assert [e.seq for e in evs] == list(range(25, 41))
    assert all(e.wall_ns > 0 and e.mono_ns > 0 for e in evs)


def test_record_survives_hostile_fields():
    class Boom:
        def __str__(self):
            raise RuntimeError("no repr for you")

    r = FlightRecorder()
    r.record("hostile", bad=Boom(), good=7)
    (ev,) = r.last(1)
    assert ev.fields == {"good": 7}  # bad field dropped, event kept


def test_wire_row_squeezes_all_whitespace():
    """A multi-line reason (an OSError message with an embedded newline)
    must not split the k=v row — that would desync the client's
    field-table framing (a fragment equal to 'END' ends the table early)."""
    r = FlightRecorder()
    r.record("storage_full", reason="line one\nEND\r\nline two\ttabbed")
    row = r.last(1)[0].wire_row()
    assert "\n" not in row and "\r" not in row and "\t" not in row
    assert "reason=line_one_END_line_two_tabbed" in row


def test_wire_dump_shape_newest_first():
    r = FlightRecorder()
    r.record("a", x=1)
    r.record("b", note="two words")
    dump = r.wire_dump(8)
    lines = dump.split("\r\n")
    assert lines[0] == "EVENTS 2"
    assert "kind=b" in lines[1] and "note=two_words" in lines[1]
    assert "kind=a" in lines[2] and "x=1" in lines[2]
    assert lines[3] == "END"


def test_record_stamps_active_trace_context():
    from merklekv_tpu.obs import tracewire

    r = FlightRecorder()
    ctx = tracewire.new_context()
    with tracewire.trace_scope(ctx):
        r.record("traced_thing")
    (ev,) = r.last(1)
    assert ev.fields.get("trace") == f"{ctx.trace_id:016x}"


# ------------------------------------------------------------------ sampler

def test_sampler_snapshots_and_derives_watch_events():
    stats = {"busy_rejected_connections": 0, "total_commands": 5}

    def stats_fn():
        return "".join(f"{k}:{v}\r\n" for k, v in stats.items())

    rec = FlightRecorder()
    s = MetricSampler(interval_s=0.05, stats_fn=stats_fn, recorder=rec)
    first = s.sample_once()
    assert first.values["native.total_commands"] == 5
    assert not [e for e in rec.last(0) if e.kind == "admission_reject"]
    stats["busy_rejected_connections"] = 7
    s.sample_once()
    evs = [e for e in rec.last(0) if e.kind == "admission_reject"]
    assert len(evs) == 1 and evs[0].fields["count"] == 7
    # no further delta -> no further event
    s.sample_once()
    assert len([e for e in rec.last(0) if e.kind == "admission_reject"]) == 1
    assert len(s.samples(0)) == 3


def test_sampler_window_is_bounded():
    s = MetricSampler(interval_s=1.0, window_s=5.0)
    for _ in range(20):
        s.sample_once()
    assert len(s.samples(0)) == 5


# -------------------------------------------------------------------- spill

def _make_doc():
    r = FlightRecorder()
    r.record("node_start", port=1234)
    r.record("degradation", prev="live", new="shedding", reason="memory")
    r.record("slow_command", verb="GET", dur_us=15000, conn="1.2.3.4:5")
    samples = [
        Sample(wall_ns=time.time_ns(),
               values={"native.total_commands": i, "keyspace.keys": 10 + i})
        for i in range(3)
    ]
    return r.last(0), samples


def test_spill_roundtrip(tmp_path):
    events, samples = _make_doc()
    path = str(tmp_path / "flight.bin")
    write_spill(path, events, samples, node="n1:1234", note="unit")
    doc = read_spill(path)
    assert not doc.truncated and doc.error == ""
    assert doc.meta["node"] == "n1:1234" and doc.meta["note"] == "unit"
    assert [e.kind for e in doc.events] == [e.kind for e in events]
    assert doc.events[1].fields["new"] == "shedding"
    assert len(doc.samples) == 3
    assert doc.samples[2].values["keyspace.keys"] == 12


def test_spill_rewrite_is_atomic(tmp_path):
    """A torn tmp write (the kill -9 shape) never disturbs the previous
    complete spill under the final name."""
    events, samples = _make_doc()
    path = str(tmp_path / "flight.bin")
    write_spill(path, events, samples, node="gen1")
    with open(path + ".tmp", "wb") as f:
        f.write(b"MKVFLT1\n\x99\x99")  # a cut-off rewrite attempt
    doc = read_spill(path)
    assert doc.meta["node"] == "gen1" and not doc.truncated


def test_spill_reader_survives_truncation_at_every_offset(tmp_path):
    """Fuzz requirement from the ISSUE: truncate the spill at EVERY byte
    offset; the reader must never raise past the magic check and must
    return an intact prefix."""
    events, samples = _make_doc()
    path = str(tmp_path / "flight.bin")
    write_spill(path, events, samples, node="n1:1")
    with open(path, "rb") as f:
        data = f.read()
    full = read_spill(path)
    # Frame boundaries: a cut exactly there is indistinguishable from a
    # shorter complete spill (no truncated flag expected); everywhere else
    # the reader must flag truncation. Either way it must never raise and
    # must return an intact prefix.
    boundaries = {len(flightrec.SPILL_MAGIC)}
    off = len(flightrec.SPILL_MAGIC)
    while off < len(data):
        (length,) = flightrec._FRAME_HDR.unpack_from(data, off)[:1]
        off += flightrec._FRAME_HDR.size + length
        boundaries.add(off)
    tpath = str(tmp_path / "trunc.bin")
    for cut in range(len(data)):
        with open(tpath, "wb") as f:
            f.write(data[:cut])
        if cut < len(flightrec.SPILL_MAGIC):
            doc = read_spill(tpath)
            assert doc.truncated and not doc.events
            continue
        doc = read_spill(tpath)
        if cut not in boundaries:
            assert doc.truncated, f"cut at {cut} not flagged"
        # the parsed prefix is always a prefix of the full doc
        assert [e.seq for e in doc.events] == [
            e.seq for e in full.events[: len(doc.events)]
        ]
        assert len(doc.samples) <= len(full.samples)


def test_spill_reader_survives_byte_flips(tmp_path):
    events, samples = _make_doc()
    path = str(tmp_path / "flight.bin")
    write_spill(path, events, samples, node="n1:1")
    with open(path, "rb") as f:
        data = f.read()
    rng = random.Random(42)
    fpath = str(tmp_path / "flip.bin")
    for _ in range(48):
        i = rng.randrange(len(flightrec.SPILL_MAGIC), len(data))
        flipped = bytearray(data)
        flipped[i] ^= 0xFF
        with open(fpath, "wb") as f:
            f.write(bytes(flipped))
        doc = read_spill(fpath)  # must not raise
        # CRC framing: a flipped payload/header byte stops parsing, it
        # never yields a silently-corrupt frame; frames before the flip
        # still parse.
        assert doc.truncated or len(doc.events) == len(events)


def test_spill_rejects_foreign_file(tmp_path):
    p = tmp_path / "notaspill.bin"
    p.write_bytes(b"definitely not a spill file\n")
    with pytest.raises(ValueError):
        read_spill(str(p))


def test_spiller_start_raises_on_unwritable_dir(tmp_path):
    """The first (inline) spill is strict: a misconfigured flight dir
    fails start() loudly so the node can disable the spiller and warn,
    instead of a background thread retrying a doomed write forever."""
    # A regular FILE where a directory is needed: makedirs fails with
    # ENOTDIR for any uid (permission bits would not stop a root test
    # runner).
    blocker = tmp_path / "blocker"
    blocker.write_text("not a directory")
    sp = FlightSpiller(str(blocker / "flight"), recorder=FlightRecorder(),
                       interval_s=30.0)
    with pytest.raises(OSError):
        sp.start()
    assert sp._thread is None  # the periodic loop never started


def test_spiller_writes_initial_and_final(tmp_path):
    rec = FlightRecorder()
    rec.record("node_start", port=1)
    sp = FlightSpiller(str(tmp_path), recorder=rec, interval_s=30.0,
                       node="n1:1")
    sp.start()  # initial spill is inline, no interval wait needed
    doc = read_spill(sp.path)
    assert [e.kind for e in doc.events] == ["node_start"]
    rec.record("node_stop")
    sp.stop(final=True)
    doc = read_spill(sp.path)
    assert [e.kind for e in doc.events] == ["node_start", "node_stop"]


# ------------------------------------------------------------ config plane

def test_config_flight_validation():
    base = {"observability": {}}
    assert Config.from_dict(base).observability.flight_enabled
    cfg = Config.from_dict(
        {"observability": {"flight_sample_s": 0.5, "flight_spill_s": 2,
                           "flight_events": 64, "slow_command_us": 500,
                           "flight_dir": "/tmp/f"}}
    )
    assert cfg.observability.flight_sample_s == 0.5
    assert cfg.observability.slow_command_us == 500
    for bad in (
        {"flight_sample_s": 0},
        {"flight_spill_s": -1},
        {"flight_events": 4},
        {"slow_command_us": -2},
    ):
        with pytest.raises(ValueError):
            Config.from_dict({"observability": bad})


def test_bench_gate_flight_overhead_is_down_good():
    from tools.bench_gate import lower_is_better

    assert lower_is_better("flight_overhead_pct", "% (median)")


# --------------------------------------------- native slow log + FLIGHT verb

def test_native_flight_fallback_serves_slow_log(server):
    eng, srv = server
    srv.set_slow_threshold(1)
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        c.set("k", "v")
        c.get("k")
        rows = c.flight(16)
        assert rows, "bare node must serve its slow-command log"
        assert all(r["kind"] == "slow_command" for r in rows)
        verbs = {r["verb"] for r in rows}
        assert {"SET", "GET"} <= verbs
        assert all(int(r["dur_us"]) >= 1 for r in rows)
        assert all(int(r["wall_ns"]) > 0 for r in rows)
        # newest first
        seqs = [int(r["seq"]) for r in rows]
        assert seqs == sorted(seqs, reverse=True)
        assert int(c.stats()["slow_commands"]) >= len(rows)


def test_slow_threshold_off_means_no_log(server):
    eng, srv = server
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        c.set("k", "v")
        assert c.flight(8) == []
        assert int(c.stats()["slow_commands"]) == 0


def test_flight_parse_errors(server):
    eng, srv = server
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        assert c._request("FLIGHT 0").startswith("ERROR")
        assert c._request("FLIGHT x").startswith("ERROR")
        assert c._request("FLIGHT 1 2").startswith("ERROR")


def test_flight_stays_open_while_loading_and_degraded(server):
    """Forensics must answer exactly when the node is sick: the FLIGHT
    verb serves through the bootstrap LOADING gate and at every
    degradation rung."""
    eng, srv = server
    srv.set_slow_threshold(1)
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        c.set("k", "v")
        srv.set_serving(False)
        try:
            assert c.flight(4)  # no ERROR LOADING
        finally:
            srv.set_serving(True)
        srv.set_degradation(2, 1)  # read_only (memory)
        try:
            assert c.flight(4)
        finally:
            srv.set_degradation(0, 0)


def test_node_flight_ring_merges_slowcmd_relay(node):
    """With a control plane attached, FLIGHT serves the python ring — and
    native slow commands reach it through the SLOWCMD notification."""
    eng, srv, n = node
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        c.set("k", "v")
        deadline = time.time() + 5
        rows = []
        while time.time() < deadline:
            rows = c.flight(32)
            if any(r["kind"] == "slow_command" for r in rows):
                break
            time.sleep(0.02)
        kinds = {r["kind"] for r in rows}
        assert "slow_command" in kinds, rows
        assert "node_start" in kinds, rows
        slow = [r for r in rows if r["kind"] == "slow_command"][0]
        assert slow["verb"] in ("SET", "GET", "PING")
        assert int(slow["dur_us"]) >= 1


def test_async_client_flight_parity(node):
    eng, srv, n = node

    async def go():
        async with AsyncMerkleKVClient("127.0.0.1", srv.port) as c:
            await c.set("ak", "av")
            await asyncio.sleep(0.05)
            return await c.flight(32)

    rows = asyncio.run(go())
    assert any(r["kind"] == "node_start" for r in rows)


def test_slow_threshold_disarmed_on_node_stop(server):
    """A stopped node must not leave its slow-command threshold armed on
    an embedded server a successor (or a flight-disabled node) reuses."""
    from merklekv_tpu.cluster.node import ClusterNode

    eng, srv = server
    cfg = Config()
    cfg.observability.slow_command_us = 1
    n = ClusterNode(cfg, eng, srv)
    n.start()
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        c.set("k", "v")
        assert int(c.stats()["slow_commands"]) > 0
        n.stop()
        before = int(c.stats()["slow_commands"])
        for i in range(5):
            c.set(f"post{i}", "v")
        assert int(c.stats()["slow_commands"]) == before


# ------------------------------------------------------------ subsystem hooks

def test_degradation_transition_records_event():
    from merklekv_tpu.cluster.overload import DegradationLadder, OverloadMonitor
    from merklekv_tpu.config import ServerConfig

    class SrvStub:
        def set_degradation(self, level, reason):
            pass

    eng = NativeEngine("mem")
    try:
        eng.set(b"k", b"v" * 128)
        rec = flightrec.get_recorder()
        rec.clear()
        mon = OverloadMonitor(
            DegradationLadder(), eng, SrvStub(),
            ServerConfig(memory_soft_bytes=1), interval=9999,
        )
        mon.poll_once()
        evs = [e for e in rec.last(0) if e.kind == "degradation"]
        assert evs and evs[-1].fields["new"] == "shedding"
        assert evs[-1].fields["prev"] == "live"
    finally:
        eng.close()


def test_storage_full_latch_and_recovery_record_events(tmp_path):
    from merklekv_tpu.config import StorageConfig
    from merklekv_tpu.storage.store import DurableStore
    from merklekv_tpu.testing.faults import WalErrnoInjector

    rec = flightrec.get_recorder()
    rec.clear()
    eng = NativeEngine("mem")
    st = DurableStore(eng, StorageConfig(), str(tmp_path))
    st.recover()
    try:
        inj = WalErrnoInjector(fail_write_at=1).install()
        try:
            eng.set_with_ts(b"k", b"v", 1)
            st.record_set(b"k", b"v", 1)
            assert st.storage_full
            inj.heal()
            st._check_disk()
            assert not st.storage_full
        finally:
            inj.uninstall()
        kinds = [e.kind for e in rec.last(0)]
        assert "storage_full" in kinds and "storage_recovered" in kinds
        assert kinds.index("storage_full") < kinds.index("storage_recovered")
    finally:
        st.stop()
        eng.close()


def test_full_backoff_resets_after_completed_snapshot(tmp_path):
    """Fast regression for the (formerly flaky) disk-full soak: a
    COMPLETED re-anchor snapshot must fully reset the probe-flap detector,
    so the NEXT genuine full episode recovers on its first post-heal
    probe instead of being deferred as a flap."""
    from merklekv_tpu.config import StorageConfig
    from merklekv_tpu.storage.store import DurableStore
    from merklekv_tpu.testing.faults import WalErrnoInjector

    eng = NativeEngine("mem")
    st = DurableStore(eng, StorageConfig(), str(tmp_path))
    st.recover()
    try:
        for cycle in (1, 2):
            inj = WalErrnoInjector(fail_write_at=1).install()
            try:
                eng.set_with_ts(b"k%d" % cycle, b"v", cycle)
                st.record_set(b"k%d" % cycle, b"v", cycle)
                assert st.storage_full
                inj.heal()
                st._check_disk()
                assert not st.storage_full, (
                    f"cycle {cycle}: recovery deferred by stale flap backoff"
                )
                st.snapshot_now()
                st._snapshot_requested = False
            finally:
                inj.uninstall()
    finally:
        st.stop()
        eng.close()


def test_sync_cycle_outcome_records_event():
    from merklekv_tpu.obs.trace import CycleTrace, PeerTrace, get_trace_buffer

    rec = flightrec.get_recorder()
    rec.clear()
    get_trace_buffer().append(
        CycleTrace(
            cycle_id=99, kind="pairwise", seconds=0.5,
            peers=[
                PeerTrace(peer="a:1", outcome="ok", repairs=2),
                PeerTrace(peer="b:2", outcome="error", error="boom"),
            ],
        )
    )
    evs = [e for e in rec.last(0) if e.kind == "sync_cycle"]
    assert evs and evs[-1].fields["outcome"] == "error"
    assert evs[-1].fields["repairs"] == 2
    assert evs[-1].fields["cycle"] == 99


def test_peer_health_flip_records_event():
    from merklekv_tpu.cluster.health import PeerHealthMonitor

    rec = flightrec.get_recorder()
    rec.clear()
    mon = PeerHealthMonitor(["127.0.0.1:1"], down_after=1, timeout=0.2)
    mon.probe_all()  # nothing listens on port 1: flips unknown -> down
    evs = [e for e in rec.last(0) if e.kind == "peer_health"]
    assert evs and evs[-1].fields["new"] == "down"
    mon.mark_degraded("x:9", "stream died")
    evs = [e for e in rec.last(0) if e.kind == "peer_health"]
    assert evs[-1].fields["new"] == "degraded"
    assert evs[-1].fields["prev"] == "unknown"  # provenance of the flip


def test_bootstrap_state_records_events():
    from merklekv_tpu.cluster.bootstrap import BootstrapSession

    rec = flightrec.get_recorder()
    rec.clear()
    sess = BootstrapSession.__new__(BootstrapSession)
    sess._state = "idle"
    sess._state_mu = threading.Lock()
    sess._enter("discover")
    sess._enter("fetch")
    states = [
        e.fields["state"] for e in rec.last(0) if e.kind == "bootstrap"
    ]
    assert states == ["discover", "fetch"]


# ---------------------------------------------------------------- blackbox

def _spill_pair(tmp_path):
    d1, d2 = tmp_path / "a", tmp_path / "b"
    r1 = FlightRecorder()
    r1.record("node_start", port=1)
    r1.record("degradation", prev="live", new="read_only", reason="disk",
              trace="cafe0000cafe0000")
    write_spill(str(d1 / "flight.bin"), r1.last(0), [], node="A:1")
    time.sleep(0.002)
    r2 = FlightRecorder()
    r2.record("node_start", port=2)
    r2.record("sync_cycle", cycle=3, outcome="error",
              trace="cafe0000cafe0000")
    write_spill(
        str(d2 / "flight.bin"),
        r2.last(0),
        [Sample(wall_ns=time.time_ns(),
                values={"replication.lag_events.A": 250})],
        node="B:2",
    )
    return str(d1), str(d2)


def test_blackbox_merges_ordered_timeline_with_trace_links(tmp_path):
    d1, d2 = _spill_pair(tmp_path)
    report = load_docs([d1, d2])
    assert not report.errors
    assert not any(doc.truncated for doc in report.docs)
    walls = [e.event.wall_ns for e in report.timeline]
    assert walls == sorted(walls)
    nodes = {e.node for e in report.timeline}
    assert nodes == {"A:1", "B:2"}
    assert report.trace_links == {"cafe0000cafe0000": ["A:1", "B:2"]}
    kinds = {a.kind for a in report.anomalies}
    assert {"degradation", "sync_failure", "lag_spike"} <= kinds


def test_blackbox_flags_device_shortfall_as_environment(tmp_path):
    """A multichip probe spill whose device-enumerate phase shows fewer
    devices than requested (MULTICHIP_r01's failure mode) surfaces as an
    ENVIRONMENT anomaly — triage reads driver weather, not a regression —
    while a full-complement enumerate stays silent."""
    d = tmp_path / "probe"
    r = FlightRecorder()
    r.record("multichip_phase", phase="device-count", want=8, have=1)
    write_spill(str(d / "flight.bin"), r.last(0), [], node="probe")
    report = load_docs([str(d)])
    envs = [a for a in report.anomalies if a.kind == "environment"]
    assert len(envs) == 1 and "have 1, want 8" in envs[0].detail

    d2 = tmp_path / "probe-ok"
    r2 = FlightRecorder()
    r2.record("multichip_phase", phase="device-count", want=8, have=8)
    write_spill(str(d2 / "flight.bin"), r2.last(0), [], node="probe")
    assert not [
        a for a in load_docs([str(d2)]).anomalies if a.kind == "environment"
    ]


def test_blackbox_cli_json_and_rc(tmp_path, capsys):
    d1, d2 = _spill_pair(tmp_path)
    rc = blackbox_main([d1, d2, "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    doc = json.loads(out)
    assert len(doc["spills"]) == 2
    assert doc["trace_links"]
    assert all(s["error"] == "" for s in doc["spills"])
    rc = blackbox_main([d1, d2])
    text = capsys.readouterr().out
    assert rc == 0
    assert "merged timeline" in text and "anomalies" in text


def test_blackbox_unreadable_input_fails_loudly(tmp_path):
    bad = tmp_path / "garbage.bin"
    bad.write_bytes(b"not a spill")
    rc = blackbox_main([str(bad)])
    assert rc == 1


def test_blackbox_fatal_marker_lands_on_timeline(tmp_path):
    d1, d2 = _spill_pair(tmp_path)
    with open(os.path.join(d1, "fatal.txt"), "w") as f:
        f.write(f"fatal signal 11 pid 77 wall_ns {time.time_ns()}\n")
    report = load_docs([d1, d2])
    fatals = [e for e in report.timeline if e.event.kind == "fatal_signal"]
    assert fatals
    # Attributed to the node whose spill shares the marker's directory —
    # NOT the directory basename (which is the same for every node in the
    # standard <data>/node-<port>/flight layout).
    assert fatals[0].node == "A:1"
    assert any(a.kind == "fatal_signal" and a.node == "A:1"
               for a in report.anomalies)


def test_merge_preserves_per_node_seq_order_under_clock_step():
    """An NTP backwards step mid-run must not reorder one node's own
    events on the merged timeline: the k-way merge interleaves nodes by
    wall clock but each node's stream stays in sequence order."""
    t = time.time_ns()
    a = flightrec.SpillDoc(
        path="a", meta={"node": "A", "pid": 11},
        events=[
            flightrec.FlightEvent(seq=1, wall_ns=t + int(5e9), mono_ns=1,
                                  kind="storage_full", fields={}),
            # clock stepped BACK 5 s between the two events
            flightrec.FlightEvent(seq=2, wall_ns=t, mono_ns=2,
                                  kind="storage_recovered", fields={}),
        ],
    )
    b = flightrec.SpillDoc(
        path="b", meta={"node": "B", "pid": 22},
        events=[
            flightrec.FlightEvent(seq=1, wall_ns=t + int(2e9), mono_ns=1,
                                  kind="node_start", fields={}),
        ],
    )
    merged = merge_timeline([a, b])
    a_kinds = [e.event.kind for e in merged if e.node == "A"]
    assert a_kinds == ["storage_full", "storage_recovered"]
    assert len(merged) == 3


def test_merge_dedupes_shared_process_ring():
    """Two co-located nodes sharing one process spill the SAME ring to
    two dirs; the analyzer must report each event once, not double-count
    every anomaly."""
    t = time.time_ns()
    evs = [
        flightrec.FlightEvent(seq=i, wall_ns=t + i, mono_ns=i,
                              kind="degradation",
                              fields={"prev": "live", "new": "shedding"})
        for i in range(1, 4)
    ]
    a = flightrec.SpillDoc(path="a", meta={"node": "A", "pid": 77},
                           events=list(evs))
    b = flightrec.SpillDoc(path="b", meta={"node": "B", "pid": 77},
                           events=list(evs))
    merged = merge_timeline([a, b])
    assert len(merged) == 3
    assert {e.node for e in merged} == {"A"}  # first doc's attribution
    # distinct pids (real distinct processes) never dedupe
    b2 = flightrec.SpillDoc(path="b", meta={"node": "B", "pid": 78},
                            events=list(evs))
    assert len(merge_timeline([a, b2])) == 6


def test_slow_burst_anomaly_window():
    r = FlightRecorder()
    for _ in range(4):
        r.record("slow_command", verb="GET", dur_us=20000, conn="x")
    doc = flightrec.SpillDoc(path="mem", meta={"node": "N"},
                             events=r.last(0))
    anomalies = find_anomalies([doc], merge_timeline([doc]))
    bursts = [a for a in anomalies if a.kind == "slow_burst"]
    assert len(bursts) == 1  # one flag per window, not one per event


# --------------------------------------------------------------------- top

def test_top_events_pane_renders():
    from merklekv_tpu.obs.top import NodeSample, render_events_pane

    s = NodeSample(node="n1:1", ok=True)
    s.events = [
        {"seq": "3", "wall_ns": str(time.time_ns()),
         "kind": "degradation", "prev": "live", "new": "shedding"},
        {"seq": "2", "wall_ns": str(time.time_ns() - int(5e9)),
         "kind": "slow_command", "verb": "GET", "dur_us": "15000"},
    ]
    pane = render_events_pane({"n1:1": s})
    assert "flight events" in pane
    assert "degradation" in pane and "new=shedding" in pane
    assert "slow_command" in pane and "verb=GET" in pane


# ------------------------------------------------------- crash marker (native)

def test_native_crash_marker_stamps_fatal_signal(tmp_path):
    """A SIGSEGV in a real process appends the async-signal-safe marker
    line before dying; blackbox reads it as a fatal_signal event."""
    marker = str(tmp_path / "fatal.txt")
    code = (
        "import ctypes, os\n"
        "from merklekv_tpu.native_bindings import install_crash_marker\n"
        f"install_crash_marker({marker!r})\n"
        "os.kill(os.getpid(), 11)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=dict(os.environ, PYTHONPATH=REPO),
        capture_output=True,
        timeout=60,
    )
    assert proc.returncode != 0  # died by signal
    with open(marker) as f:
        line = f.read()
    assert line.startswith("fatal signal 11 pid ")
    assert "wall_ns" in line
    from merklekv_tpu.obs.blackbox import _marker_events

    evs = _marker_events(marker)
    assert evs and evs[0].kind == "fatal_signal"
    assert evs[0].fields["signal"] == 11


# ------------------------------------------------- kill -9 chaos (integration)

def _flight_toml(path, port, data_dir):
    path.write_text(
        f"""
host = "127.0.0.1"
port = {port}
engine = "mem"
storage_path = "{data_dir}"

[storage]
enabled = true
fsync = "always"
merkle_engine = "cpu"

[observability]
flight_spill_s = 0.2
flight_sample_s = 0.1
slow_command_us = 1
"""
    )
    return str(path)


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        sk = socket.socket()
        sk.bind(("127.0.0.1", 0))
        ports.append(sk.getsockname()[1])
        socks.append(sk)
    for sk in socks:
        sk.close()
    return ports


@pytest.mark.integration
def test_kill9_midburst_leaves_parseable_spill_and_blackbox_merges(tmp_path):
    """The acceptance core: SIGKILL two durable nodes mid-write-burst; each
    surviving spill parses with zero errors, its tail names the final
    state transitions (and proves the death was NOT clean — no node_stop),
    and blackbox merges both into one ordered timeline, rc 0."""
    ports = _free_ports(2)
    procs = []
    try:
        for i, port in enumerate(ports):
            toml = _flight_toml(
                tmp_path / f"n{i}.toml", port, str(tmp_path / f"data{i}")
            )
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-m", "merklekv_tpu", "--config", toml],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                    env=dict(os.environ, PYTHONPATH=REPO,
                             JAX_PLATFORMS="cpu"),
                )
            )
        for proc, port in zip(procs, ports):
            line = proc.stdout.readline()
            assert "listening on" in line, line
        clients = [
            MerkleKVClient("127.0.0.1", p).connect() for p in ports
        ]
        stop = threading.Event()

        def burst(c, tag):
            i = 0
            try:
                while not stop.is_set():
                    c.set(f"{tag}:{i:06d}", "v" * 32)
                    i += 1
            except Exception:
                pass  # the kill severs the connection — expected

        threads = [
            threading.Thread(target=burst, args=(c, t), daemon=True)
            for c, t in zip(clients, ("a", "b"))
        ]
        for t in threads:
            t.start()
        time.sleep(1.0)  # several spill intervals land mid-burst
        for proc in procs:
            os.kill(proc.pid, signal.SIGKILL)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        for c in clients:
            c.close()
        for proc in procs:
            proc.wait(timeout=10)

        flight_dirs = [
            os.path.join(str(tmp_path / f"data{i}"), f"node-{p}", "flight")
            for i, p in enumerate(ports)
        ]
        docs = []
        for d in flight_dirs:
            doc = read_spill(os.path.join(d, "flight.bin"))
            # Atomic rewrite: the surviving spill is COMPLETE, zero parse
            # errors, even though the process died mid-burst.
            assert not doc.truncated and doc.error == ""
            kinds = [e.kind for e in doc.events]
            # node_start is present unless the 1 us threshold flooded the
            # ring past capacity — in which case the rolled sequence
            # numbers prove the recorder kept running to the end.
            assert "node_start" in kinds or doc.events[0].seq > 1
            # the tail names the final transitions: the burst's slow
            # commands (1 us threshold) ran to the very end...
            assert doc.events[-1].kind in (
                "slow_command", "admission_reject", "events_dropped",
                "writes_shed",
            ), kinds[-5:]
            assert "slow_command" in kinds
            # ...and there is NO clean-shutdown marker: the spill alone
            # distinguishes kill -9 from a stop().
            assert "node_stop" not in kinds
            assert len(doc.samples) >= 2
            docs.append(doc)

        rc = blackbox_main([*flight_dirs, "--json"])
        assert rc == 0
        report = load_docs(flight_dirs)
        assert not report.errors
        assert {d.node for d in report.docs} == {
            f"127.0.0.1:{p}" for p in ports
        }
        walls = [e.event.wall_ns for e in report.timeline]
        assert walls == sorted(walls)
        assert len(report.timeline) >= len(docs[0].events)
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()

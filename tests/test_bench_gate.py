"""CI bench-regression gate (tools/bench_gate.py): scenario extraction
from driver round records (parsed headline + stderr-tail JSON lines),
direction-aware >20% regression detection, and the skip rules for
crashed/unusable rounds."""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "tools"),
)

import bench_gate  # noqa: E402


def _round(headline_value, tail_scenarios, rc=0):
    tail = "some noise\n" + "".join(
        json.dumps(s) + "\n" for s in tail_scenarios
    )
    rec = {"rc": rc, "tail": tail}
    if headline_value is not None:
        rec["parsed"] = {
            "metric": "merkle_rebuild_diff_keys_per_s",
            "value": headline_value,
            "unit": "keys/s",
        }
    return rec


def test_extract_scenarios_headline_and_tail():
    rec = _round(1000.0, [
        {"metric": "op_latency_us", "value": 15.0, "unit": "us (GET p50)"},
        {"metric": "broken", "value": None},
        {"not_a": "scenario"},
    ])
    out = bench_gate.extract_scenarios(rec)
    assert set(out) == {"merkle_rebuild_diff_keys_per_s", "op_latency_us"}


def test_extract_tolerates_truncated_tail():
    rec = {"rc": 0, "tail": '{"metric": "x", "val'}  # driver tail cut
    assert bench_gate.extract_scenarios(rec) == {}


def test_direction_rules():
    assert not bench_gate.lower_is_better("merkle_rebuild", "keys/s")
    assert not bench_gate.lower_is_better("rep", "events/s (batched)")
    assert bench_gate.lower_is_better("op_latency_us", "us (GET p50)")
    assert bench_gate.lower_is_better("cycle_p50_ms", "ms")
    assert bench_gate.lower_is_better("sync_wire_bytes_1key",
                                      "bytes (bisect walk)")
    assert bench_gate.lower_is_better("set_metrics_overhead_pct",
                                      "% (median)")
    # The many-connection pipelined scenario gates as throughput: its
    # aggregate ops/s must not DROP round-over-round.
    assert not bench_gate.lower_is_better(
        "many_conn_throughput",
        "ops/s (64 conns x pipelined GET/SET, depth 32)",
    )
    assert not bench_gate.lower_is_better("overload_goodput",
                                          "ops/s (accepted)")
    # Asynchronous-maintenance scenario gates on write p99: LOWER is
    # better — the pump path's latency regressing toward force-on-query
    # cost is exactly what the gate must catch.
    assert bench_gate.lower_is_better(
        "tree_freshness_write_p99_us",
        "us (SET p99 under concurrent TREELEVEL load, pump path)",
    )
    # Sharded-plane scenario gates as throughput: mesh rebuild+diff keys/s
    # must not DROP — a change that serializes the per-shard subtree
    # reduction (or breaks the all_gather top tree back to host hashing)
    # is exactly what this direction pins.
    assert not bench_gate.lower_is_better(
        "sharded_rebuild_diff_keys_per_s",
        "keys/s (rebuild + 8-replica diff over the key mesh)",
    )
    # Zero-copy serving A/B: GB/s is throughput (must not DROP)...
    assert not bench_gate.lower_is_better(
        "large_value_throughput",
        "GB/s (64 conns pipelined GET, 1MiB hot values)",
    )
    # ...while serve-path allocations/op is a per-op COST, not a rate:
    # the "/op" unit (and the _per_op suffix) must read down-good, or a
    # change that reintroduces the serve copy would gate as an
    # improvement.
    assert bench_gate.lower_is_better("large_value_alloc_per_op",
                                      "allocs/op")
    assert bench_gate.lower_is_better("anything_per_op", "")
    # Request-plane scenarios (PR 17) gate as throughput: the pooled
    # router's pipelined ops/s and the skewed-load cached GET rate must
    # not DROP — an io-plane change that serializes the upstream fan-out
    # or breaks the lease cache is what these directions pin.
    assert not bench_gate.lower_is_better(
        "router_pipelined_throughput",
        "ops/s (64 conns x pipelined GET/SET via router, depth 32)",
    )
    assert not bench_gate.lower_is_better(
        "router_hotkey_skew",
        "gets/s (router, Zipf(0.5) over 512 keys, 4ms emulated "
        "partition RTT)",
    )


def test_compare_flags_only_real_regressions():
    prev = {
        "throughput": {"value": 100.0, "unit": "keys/s"},
        "latency": {"value": 10.0, "unit": "ms"},
        "only_prev": {"value": 1.0, "unit": "ms"},
    }
    cur = {
        "throughput": {"value": 85.0, "unit": "keys/s"},   # -15%: ok
        "latency": {"value": 11.5, "unit": "ms"},          # +15%: ok
        "only_cur": {"value": 1.0, "unit": "ms"},
    }
    assert bench_gate.compare(prev, cur) == []
    cur["throughput"]["value"] = 70.0  # -30%: regression
    cur["latency"]["value"] = 14.0     # +40%: regression
    lines = bench_gate.compare(prev, cur)
    assert len(lines) == 2
    assert any("throughput" in ln for ln in lines)
    assert any("latency" in ln for ln in lines)


def test_main_passes_and_fails(tmp_path, capsys):
    a = tmp_path / "BENCH_r01.json"
    b = tmp_path / "BENCH_r02.json"
    a.write_text(json.dumps(_round(1000.0, [
        {"metric": "op_latency_us", "value": 10.0, "unit": "us"}])))
    b.write_text(json.dumps(_round(990.0, [
        {"metric": "op_latency_us", "value": 11.0, "unit": "us"}])))
    assert bench_gate.main(["--dir", str(tmp_path)]) == 0
    b.write_text(json.dumps(_round(990.0, [
        {"metric": "op_latency_us", "value": 30.0, "unit": "us"}])))
    assert bench_gate.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION op_latency_us" in out


def test_main_skips_crashed_rounds(tmp_path):
    """A crashed newest round (rc=1, no scenarios) must not become the
    baseline OR the candidate; with only one usable round the gate warns
    and passes."""
    good = tmp_path / "BENCH_r01.json"
    bad = tmp_path / "BENCH_r02.json"
    good.write_text(json.dumps(_round(1000.0, [])))
    bad.write_text(json.dumps({"rc": 1, "tail": "Traceback ..."}))
    assert bench_gate.main(["--dir", str(tmp_path)]) == 0


def test_main_gates_on_committed_rounds_in_repo():
    """The real committed BENCH_r*.json history must pass the gate (CI
    runs exactly this)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert bench_gate.main(["--dir", repo]) == 0


def test_router_pipelined_throughput_runs_green_on_cpu():
    """Weather test: the request-plane io A/B scenario must RUN on a
    plain CPU box at reduced size — cluster spin-up, both router builds,
    the burst drive, and teardown all green, emitting a gateable record
    (usable value, both sides present). Perf targets are the real-size
    run's business, not this one's."""
    import bench

    rec = bench.bench_router_pipelined_throughput(
        n_conns=16, depth=8, bursts=4
    )
    assert rec["metric"] == "router_pipelined_throughput"
    assert isinstance(rec["value"], (int, float)) and rec["value"] > 0
    assert rec["pooled_ops_per_s"] > 0
    assert rec["legacy_ops_per_s"] > 0
    assert rec["speedup_x"] > 0
    assert not bench_gate.lower_is_better(rec["metric"], rec["unit"])


def test_router_hotkey_skew_runs_green_on_cpu():
    """Weather test: the Zipfian A/B scenario must RUN on a plain CPU
    box at reduced size — delay proxies, replication feed, lease cache,
    all four corners measured, teardown green. Direction sanity rides
    along; the uniform/skew acceptance corners are the real-size run's
    business."""
    import bench

    rec = bench.bench_router_hotkey_skew(
        duration_s=0.4, n_keys=128, readers=4, rtt_ms=2.0, workers=2,
        cache_entries=48,
    )
    assert rec["metric"] == "router_hotkey_skew"
    assert isinstance(rec["value"], (int, float)) and rec["value"] > 0
    for corner in (
        "uniform_smart_gets_per_s", "uniform_router_gets_per_s",
        "skew_smart_gets_per_s", "skew_router_gets_per_s",
    ):
        assert rec[corner] > 0
    assert rec["uniform_router_p99_ms"] > 0
    assert not bench_gate.lower_is_better(rec["metric"], rec["unit"])

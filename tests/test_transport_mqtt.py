"""MQTT 3.1.1 transport (VERDICT r4 item 6).

Frame-level tests against MqttBroker (real MQTT wire frames on real
sockets — CONNACK/SUBACK/PUBLISH fan-out/PINGRESP), plus end-to-end
replication between two ClusterNodes whose fabric is `transport = "mqtt"`.
"""

import socket
import struct
import time
import uuid

import pytest

from merklekv_tpu.cluster.transport_mqtt import (
    MqttTransport,
    MqttBroker,
    _topic_matches,
)


@pytest.fixture
def broker():
    b = MqttBroker()
    yield b
    b.close()


def wait_for(fn, timeout=5.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def test_topic_filter_matching():
    assert _topic_matches("a/events/#", "a/events")  # parent level
    assert _topic_matches("a/events/#", "a/events/x/y")
    assert not _topic_matches("a/events/#", "a/other")
    assert _topic_matches("a/+/c", "a/b/c")
    assert not _topic_matches("a/+/c", "a/b/d")
    assert not _topic_matches("a/+", "a/b/c")
    assert _topic_matches("#", "anything/at/all")


def test_connect_publish_subscribe_round_trip(broker):
    got = []
    t1 = MqttTransport(broker.host, broker.port, client_id="c1")
    t2 = MqttTransport(broker.host, broker.port, client_id="c2")
    try:
        t2.subscribe("t/events", lambda topic, p: got.append((topic, p)))
        time.sleep(0.05)  # let SUBACK land before publishing
        t1.publish("t/events", b"payload-1")
        assert wait_for(lambda: got == [("t/events", b"payload-1")])
        assert broker.connects == 2
        assert broker.publishes >= 1
    finally:
        t1.close()
        t2.close()


def test_publisher_receives_own_messages_like_mqtt(broker):
    """MQTT fan-out includes the publisher when it subscribes — the
    replicator's src-based loop prevention depends on seeing (and
    skipping) its own events, same as with a real broker."""
    got = []
    t = MqttTransport(broker.host, broker.port, client_id="self")
    try:
        t.subscribe("s/events", lambda topic, p: got.append(p))
        time.sleep(0.05)
        t.publish("s/events", b"echo")
        assert wait_for(lambda: got == [b"echo"])
    finally:
        t.close()


def test_frames_are_real_mqtt(broker):
    """Hand-rolled socket speaking raw MQTT 3.1.1 frames interoperates
    with the broker — proving the wire format, not just the Python API."""
    sock = socket.create_connection((broker.host, broker.port), timeout=5)
    try:
        # CONNECT: protocol name "MQTT", level 4, clean session.
        cid = b"rawcli"
        var = struct.pack(">H", 4) + b"MQTT" + bytes([4, 0x02]) + struct.pack(">H", 30)
        payload = struct.pack(">H", len(cid)) + cid
        body = var + payload
        sock.sendall(bytes([0x10, len(body)]) + body)
        connack = sock.recv(4)
        assert connack == bytes([0x20, 2, 0, 0])

        # SUBSCRIBE to raw/#
        filt = b"raw/#"
        body = struct.pack(">H", 7) + struct.pack(">H", len(filt)) + filt + b"\x00"
        sock.sendall(bytes([0x82, len(body)]) + body)
        suback = sock.recv(5)
        assert suback == bytes([0x90, 3, 0, 7, 0])

        # PUBLISH from a transport client; the raw socket must receive a
        # spec-shaped PUBLISH frame.
        t = MqttTransport(broker.host, broker.port, client_id="pub")
        try:
            t.publish("raw/events", b"xyz")
            sock.settimeout(5)
            frame = sock.recv(256)
            assert frame[0] == 0x30  # PUBLISH, QoS-0
            rem = frame[1]
            (tlen,) = struct.unpack(">H", frame[2:4])
            assert frame[4 : 4 + tlen] == b"raw/events"
            assert frame[4 + tlen :] == b"xyz"
            assert rem == 2 + tlen + 3
        finally:
            t.close()
    finally:
        sock.close()


def test_ping_keepalive(broker):
    t = MqttTransport(broker.host, broker.port, client_id="ping", keepalive=2)
    try:
        # The ping loop fires at keepalive/2 = 1s; surviving 2.5s proves
        # PINGREQ/PINGRESP round-trips don't wedge the read loop.
        got = []
        t.subscribe("ka/events", lambda topic, p: got.append(p))
        time.sleep(2.5)
        t.publish("ka/events", b"alive")
        assert wait_for(lambda: got == [b"alive"])
    finally:
        t.close()


def test_auth_fields_accepted(broker):
    t = MqttTransport(
        broker.host, broker.port, client_id="auth",
        username="u", password="p",
    )
    t.close()
    assert broker.connects >= 1


@pytest.mark.integration
def test_replication_over_mqtt_fabric(broker):
    """Two ClusterNodes whose [replication] transport = "mqtt" converge
    through the (stub, frame-accurate) MQTT broker."""
    from merklekv_tpu.client import MerkleKVClient
    from merklekv_tpu.cluster.node import ClusterNode
    from merklekv_tpu.config import Config
    from merklekv_tpu.native_bindings import NativeEngine, NativeServer

    topic = f"mq-{uuid.uuid4().hex[:8]}"

    def make_node(node_id):
        engine = NativeEngine("mem")
        server = NativeServer(engine, "127.0.0.1", 0)
        server.start()
        cfg = Config()
        cfg.replication.enabled = True
        cfg.replication.transport = "mqtt"
        cfg.replication.mqtt_broker = broker.host
        cfg.replication.mqtt_port = broker.port
        cfg.replication.topic_prefix = topic
        cfg.replication.client_id = node_id
        node = ClusterNode(cfg, engine, server)
        node.start()
        client = MerkleKVClient("127.0.0.1", server.port, timeout=15).connect()
        return engine, server, node, client

    e1, s1, n1, c1 = make_node("mq-1")
    e2, s2, n2, c2 = make_node("mq-2")
    try:
        c1.set("mqtt-key", "mqtt-value")
        assert wait_for(lambda: c2.get("mqtt-key") == "mqtt-value")
        c2.set("reverse", "path")
        assert wait_for(lambda: c1.get("reverse") == "path")
        c1.delete("mqtt-key")
        assert wait_for(lambda: c2.get("mqtt-key") is None)
        assert wait_for(lambda: c1.hash() == c2.hash())
    finally:
        for cl, nd, sv, en in ((c1, n1, s1, e1), (c2, n2, s2, e2)):
            cl.close()
            nd.stop()
            sv.close()
            en.close()


def test_unknown_transport_kind_rejected():
    from merklekv_tpu.cluster.transport import make_transport

    with pytest.raises(ValueError, match="unknown replication transport"):
        make_transport("somehost", 1883, kind="MQTT")  # typo'd case


def _raw_connect(broker) -> socket.socket:
    """Minimal third-party-style MQTT client: CONNECT and eat the CONNACK."""
    from merklekv_tpu.cluster.transport_mqtt import _encode_varlen, _utf8

    s = socket.create_connection((broker.host, broker.port), timeout=5)
    var = _utf8("MQTT") + bytes([4, 0x02]) + struct.pack(">H", 30)
    body = var + _utf8(f"raw-{uuid.uuid4().hex[:8]}")
    s.sendall(bytes([0x10]) + _encode_varlen(len(body)) + body)
    ack = s.recv(4)
    assert ack == bytes([0x20, 2, 0, 0]), ack
    return s


def test_qos1_publish_from_third_party_client(broker):
    """A QoS-1 publisher (mosquitto_pub -q 1 style) gets a PUBACK, and
    subscribers receive a CLEAN QoS-0 body — no stray packet-id bytes."""
    from merklekv_tpu.cluster.transport_mqtt import _encode_varlen, _utf8

    got = []
    sub = MqttTransport(broker.host, broker.port, client_id="q1sub")
    try:
        sub.subscribe("q1/events", lambda t, p: got.append((t, p)))
        time.sleep(0.05)
        raw = _raw_connect(broker)
        try:
            body = _utf8("q1/events/k") + struct.pack(">H", 77) + b"payload-q1"
            raw.sendall(bytes([0x32]) + _encode_varlen(len(body)) + body)
            puback = raw.recv(4)
            assert puback == bytes([0x40, 2, 0, 77]), puback
            assert wait_for(lambda: got == [("q1/events/k", b"payload-q1")]), got
        finally:
            raw.close()
    finally:
        sub.close()


def test_malformed_frame_drops_sender_only(broker):
    """An empty-body PUBLISH (malformed: no topic length) must cost the
    sender its connection and nothing else — the broker keeps serving."""
    bad = _raw_connect(broker)
    bad.sendall(bytes([0x30, 0x00]))  # PUBLISH, remaining length 0
    # Broker closes the offender (recv sees EOF within the timeout).
    bad.settimeout(5)
    assert bad.recv(16) == b""
    bad.close()

    got = []
    t1 = MqttTransport(broker.host, broker.port, client_id="after-bad-1")
    t2 = MqttTransport(broker.host, broker.port, client_id="after-bad-2")
    try:
        t2.subscribe("ok/events", lambda t, p: got.append(p))
        time.sleep(0.05)
        t1.publish("ok/events", b"still-alive")
        assert wait_for(lambda: got == [b"still-alive"])
    finally:
        t1.close()
        t2.close()


@pytest.mark.integration
def test_mqtt_broker_cli_cluster_replicates(tmp_path):
    """All-MQTT cluster, fully self-contained: the CLI broker in --protocol
    mqtt mode plus two server processes configured with transport="mqtt"
    must replicate writes end-to-end through real MQTT 3.1.1 frames."""
    import os
    import subprocess
    import sys

    from merklekv_tpu.client import MerkleKVClient

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # Server processes must not race for the single tunneled TPU.
    env = dict(os.environ, PYTHONPATH=repo, MERKLEKV_JAX_PLATFORM="cpu")
    procs = []

    def spawn(args):
        p = subprocess.Popen(
            [sys.executable, *args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        procs.append(p)
        return p

    try:
        broker = spawn(["-m", "merklekv_tpu.broker", "--port", "0",
                        "--protocol", "mqtt"])
        line = broker.stdout.readline()
        assert "(mqtt) listening on" in line, line
        broker_port = int(line.rsplit(":", 1)[1].split()[0])

        ports = []
        for i in (1, 2):
            cfg = tmp_path / f"node{i}.toml"
            cfg.write_text(f"""
host = "127.0.0.1"
port = 0
engine = "mem"

[replication]
enabled = true
transport = "mqtt"
mqtt_broker = "127.0.0.1"
mqtt_port = {broker_port}
topic_prefix = "mqtt_itest"
client_id = "mq-node-{i}"
""")
            p = spawn(["-m", "merklekv_tpu", "--config", str(cfg)])
            line = p.stdout.readline()
            assert "listening on" in line, line
            ports.append(int(line.rsplit(":", 1)[1].split()[0]))

        with MerkleKVClient("127.0.0.1", ports[0]) as a, \
             MerkleKVClient("127.0.0.1", ports[1]) as b:
            a.set("mq:x", "from-a")
            b.set("mq:y", "from-b")
            deadline = time.time() + 15
            while time.time() < deadline:
                if b.get("mq:x") == "from-a" and a.get("mq:y") == "from-b":
                    break
                time.sleep(0.1)
            assert b.get("mq:x") == "from-a"
            assert a.get("mq:y") == "from-b"
            a.set("mq:del", "gone")
            deadline = time.time() + 15
            while time.time() < deadline and b.get("mq:del") != "gone":
                time.sleep(0.1)
            assert b.get("mq:del") == "gone"  # SET replicated before DEL
            a.delete("mq:del")
            deadline = time.time() + 15
            while time.time() < deadline and b.get("mq:del") is not None:
                time.sleep(0.1)
            assert b.get("mq:del") is None
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


def test_mqtt_transport_reconnects_after_broker_restart():
    """Broker restart heals the MQTT fabric: the transport re-dials,
    re-handshakes, and RE-SUBSCRIBES (clean-session brokers forget filters)
    — rumqttc behavior (/root/reference/src/replication.rs:148-166)."""
    broker = MqttBroker()
    port = broker.port
    t_pub = MqttTransport(broker.host, port, client_id="rc-pub")
    t_sub = MqttTransport(broker.host, port, client_id="rc-sub")
    got = []
    try:
        t_sub.subscribe("mrc/events", lambda topic, p: got.append(p))
        time.sleep(0.05)
        t_pub.publish("mrc/events", b"before")
        assert wait_for(lambda: got == [b"before"])

        broker.close()
        deadline = time.time() + 10
        broker = None
        while time.time() < deadline and broker is None:
            try:
                broker = MqttBroker(port=port)
            except OSError:
                time.sleep(0.1)
        assert broker is not None, "broker could not rebind its port"

        assert wait_for(
            lambda: t_pub.reconnects >= 1 and t_sub.reconnects >= 1,
            timeout=15,
        ), (t_pub.reconnects, t_sub.reconnects)

        # The resubscribed filter must actually deliver.
        deadline = time.time() + 10
        while time.time() < deadline and b"after" not in got:
            t_pub.publish("mrc/events", b"after")
            time.sleep(0.1)
        assert b"after" in got
    finally:
        t_pub.close()
        t_sub.close()
        if broker is not None:
            broker.close()


def test_mqtt_outbox_flushes_after_heal():
    """Events published during a DETECTED broker outage are buffered and
    delivered (after resubscribe) once the link heals."""
    broker = MqttBroker()
    port = broker.port
    t_pub = MqttTransport(broker.host, port, client_id="ob-pub")
    t_sub = MqttTransport(broker.host, port, client_id="ob-sub")
    # The publisher's post-heal drain races the subscriber's resubscribe
    # (QoS-0 has no cross-client ordering); stagger the publisher's first
    # retry so the subscriber deterministically heals first.
    t_pub._BACKOFF_FIRST = 1.5
    got = []
    try:
        t_sub.subscribe("mob/events", lambda topic, p: got.append(p))
        time.sleep(0.05)
        broker.close()
        assert wait_for(lambda: t_pub.link_down and t_sub.link_down), (
            t_pub.link_down, t_sub.link_down
        )
        for i in range(5):
            t_pub.publish("mob/events", b"d-%d" % i)
        assert got == []
        deadline = time.time() + 10
        broker = None
        while time.time() < deadline and broker is None:
            try:
                broker = MqttBroker(port=port)
            except OSError:
                time.sleep(0.1)
        assert broker is not None, "broker could not rebind its port"
        assert wait_for(lambda: len(got) >= 5, timeout=15), got
        assert got == [b"d-%d" % i for i in range(5)]
    finally:
        t_pub.close()
        t_sub.close()
        if broker is not None:
            broker.close()

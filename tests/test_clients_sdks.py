"""Go / Node.js SDK suites against a spawned native server.

Each SDK carries its own test suite (clients/go/client_test.go,
clients/nodejs/test.js); this harness spawns one embedded server and runs
them with MERKLEKV_PORT pointed at it — the reference's clients-ci.yml
pattern (/root/reference/.github/workflows/clients-ci.yml). Skipped when the
toolchain isn't installed (this image has neither; CI does).
"""

import os
import shutil
import subprocess

import pytest

from merklekv_tpu.native_bindings import NativeEngine, NativeServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def server_port():
    engine = NativeEngine("mem")
    server = NativeServer(engine, "127.0.0.1", 0)
    server.start()
    yield server.port
    server.close()
    engine.close()


@pytest.mark.integration
def test_go_client_suite(server_port):
    go = shutil.which("go")
    if go is None:
        pytest.skip("go toolchain not installed")
    env = dict(os.environ, MERKLEKV_PORT=str(server_port))
    r = subprocess.run(
        [go, "test", "-v", "./..."],
        cwd=os.path.join(REPO, "clients", "go"),
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "SKIP" not in r.stdout, "go suite skipped instead of running"


@pytest.mark.integration
def test_node_client_suite(server_port):
    node = shutil.which("node")
    if node is None:
        pytest.skip("node toolchain not installed")
    env = dict(os.environ, MERKLEKV_PORT=str(server_port))
    r = subprocess.run(
        [node, "--test", "test.js"],
        cwd=os.path.join(REPO, "clients", "nodejs"),
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr


@pytest.mark.integration
def test_java_client_suite(server_port, tmp_path):
    javac = shutil.which("javac")
    if javac is None or shutil.which("java") is None:
        pytest.skip("java toolchain not installed")
    jdir = os.path.join(REPO, "clients", "java")
    env = dict(os.environ, MERKLEKV_PORT=str(server_port))
    r = subprocess.run(
        [javac, "-d", str(tmp_path),
         os.path.join(jdir, "src/main/java/io/merklekv/client/MerkleKVClient.java"),
         os.path.join(jdir, "src/test/java/io/merklekv/client/ClientSelfTest.java")],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr
    r = subprocess.run(
        ["java", "-cp", str(tmp_path), "io.merklekv.client.ClientSelfTest"],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "JAVA CLIENT PASS" in r.stdout, r.stdout


@pytest.mark.integration
def test_ruby_client_suite(server_port):
    ruby = shutil.which("ruby")
    if ruby is None:
        pytest.skip("ruby toolchain not installed")
    env = dict(os.environ, MERKLEKV_PORT=str(server_port))
    r = subprocess.run(
        [ruby, "test_merklekv.rb"],
        cwd=os.path.join(REPO, "clients", "ruby"),
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 failures, 0 errors, 0 skips" in r.stdout, r.stdout

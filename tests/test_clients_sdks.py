"""Client SDK suites against a spawned native server.

Each SDK carries its own test suite (clients/go/client_test.go,
clients/nodejs/test.js, clients/php/test.php, clients/rust/tests/,
clients/dotnet/ClientSelfTest.cs, clients/kotlin + clients/scala self-test
mains, clients/elixir/test/); this harness spawns one embedded server and
runs them with MERKLEKV_PORT pointed at it — the reference's clients-ci.yml
pattern (/root/reference/.github/workflows/clients-ci.yml). Skipped when the
toolchain isn't installed (this image has none of them; CI does).
"""

import os
import shutil
import subprocess

import pytest

from merklekv_tpu.native_bindings import NativeEngine, NativeServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def server_port():
    engine = NativeEngine("mem")
    server = NativeServer(engine, "127.0.0.1", 0)
    server.start()
    yield server.port
    server.close()
    engine.close()


@pytest.mark.integration
def test_go_client_suite(server_port):
    go = shutil.which("go")
    if go is None:
        pytest.skip("go toolchain not installed")
    env = dict(os.environ, MERKLEKV_PORT=str(server_port))
    r = subprocess.run(
        [go, "test", "-v", "./..."],
        cwd=os.path.join(REPO, "clients", "go"),
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "SKIP" not in r.stdout, "go suite skipped instead of running"


@pytest.mark.integration
def test_node_client_suite(server_port):
    node = shutil.which("node")
    if node is None:
        pytest.skip("node toolchain not installed")
    env = dict(os.environ, MERKLEKV_PORT=str(server_port))
    r = subprocess.run(
        [node, "--test", "test.js"],
        cwd=os.path.join(REPO, "clients", "nodejs"),
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr


@pytest.mark.integration
def test_java_client_suite(server_port, tmp_path):
    javac = shutil.which("javac")
    if javac is None or shutil.which("java") is None:
        pytest.skip("java toolchain not installed")
    jdir = os.path.join(REPO, "clients", "java")
    env = dict(os.environ, MERKLEKV_PORT=str(server_port))
    r = subprocess.run(
        [javac, "-d", str(tmp_path),
         os.path.join(jdir, "src/main/java/io/merklekv/client/MerkleKVClient.java"),
         os.path.join(jdir, "src/test/java/io/merklekv/client/ClientSelfTest.java")],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr
    r = subprocess.run(
        ["java", "-cp", str(tmp_path), "io.merklekv.client.ClientSelfTest"],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "JAVA CLIENT PASS" in r.stdout, r.stdout


@pytest.mark.integration
def test_ruby_client_suite(server_port):
    ruby = shutil.which("ruby")
    if ruby is None:
        pytest.skip("ruby toolchain not installed")
    env = dict(os.environ, MERKLEKV_PORT=str(server_port))
    r = subprocess.run(
        [ruby, "test_merklekv.rb"],
        cwd=os.path.join(REPO, "clients", "ruby"),
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 failures, 0 errors, 0 skips" in r.stdout, r.stdout


@pytest.mark.integration
def test_php_client_suite(server_port):
    php = shutil.which("php")
    if php is None:
        pytest.skip("php toolchain not installed")
    env = dict(os.environ, MERKLEKV_PORT=str(server_port))
    r = subprocess.run(
        [php, "test.php"],
        cwd=os.path.join(REPO, "clients", "php"),
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PHP CLIENT PASS" in r.stdout, r.stdout
    assert "SKIP" not in r.stdout, "php suite skipped instead of running"


@pytest.mark.integration
def test_rust_client_suite(server_port):
    cargo = shutil.which("cargo")
    if cargo is None:
        pytest.skip("rust toolchain not installed")
    env = dict(os.environ, MERKLEKV_PORT=str(server_port))
    r = subprocess.run(
        [cargo, "test", "--", "--nocapture"],
        cwd=os.path.join(REPO, "clients", "rust"),
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "SKIP: no server reachable" not in (r.stdout + r.stderr), (
        "rust suite skipped instead of running"
    )


@pytest.mark.integration
def test_dotnet_client_suite(server_port):
    dotnet = shutil.which("dotnet")
    if dotnet is None:
        pytest.skip("dotnet toolchain not installed")
    env = dict(os.environ, MERKLEKV_PORT=str(server_port))
    r = subprocess.run(
        [dotnet, "run"],
        cwd=os.path.join(REPO, "clients", "dotnet"),
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "DOTNET CLIENT PASS" in r.stdout, r.stdout


@pytest.mark.integration
def test_kotlin_client_suite(server_port, tmp_path):
    kotlinc = shutil.which("kotlinc")
    if kotlinc is None or shutil.which("java") is None:
        pytest.skip("kotlin toolchain not installed")
    kdir = os.path.join(REPO, "clients", "kotlin")
    jar = str(tmp_path / "selftest.jar")
    r = subprocess.run(
        [kotlinc,
         os.path.join(kdir, "src/main/kotlin/io/merklekv/client/MerkleKVClient.kt"),
         os.path.join(kdir, "src/test/kotlin/io/merklekv/client/ClientSelfTest.kt"),
         "-include-runtime", "-d", jar],
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr
    env = dict(os.environ, MERKLEKV_PORT=str(server_port))
    r = subprocess.run(
        ["java", "-jar", jar], env=env,
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "KOTLIN CLIENT PASS" in r.stdout, r.stdout


@pytest.mark.integration
def test_scala_client_suite(server_port, tmp_path):
    scalac = shutil.which("scalac")
    if scalac is None or shutil.which("scala") is None:
        pytest.skip("scala toolchain not installed")
    sdir = os.path.join(REPO, "clients", "scala")
    out = str(tmp_path / "selftest")
    r = subprocess.run(
        [scalac,
         os.path.join(sdir, "src/main/scala/io/merklekv/client/MerkleKVClient.scala"),
         os.path.join(sdir, "src/test/scala/io/merklekv/client/ClientSelfTest.scala"),
         "-d", out],
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr
    env = dict(os.environ, MERKLEKV_PORT=str(server_port))
    r = subprocess.run(
        ["scala", "-cp", out, "io.merklekv.client.ClientSelfTest"], env=env,
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "SCALA CLIENT PASS" in r.stdout, r.stdout


@pytest.mark.integration
def test_elixir_client_suite(server_port):
    elixir = shutil.which("elixir")
    if elixir is None:
        pytest.skip("elixir toolchain not installed")
    env = dict(os.environ, MERKLEKV_PORT=str(server_port))
    r = subprocess.run(
        [elixir, "-r", "lib/merklekv.ex", "test/merklekv_test.exs"],
        cwd=os.path.join(REPO, "clients", "elixir"),
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ELIXIR CLIENT PASS" in r.stdout, r.stdout

"""Causal tracing (obs/tracewire.py): token grammar, span collection,
cross-node stitching, fault tolerance of the stitch, capability fallback
against pre-tracing peers, and the Perfetto assembly + CLI.

The acceptance case (ISSUE 7): a 3-node cycle produces ONE stitched,
Perfetto-loadable trace with spans from BOTH peers under one trace id —
and fault injection on the link can orphan spans but never mis-parent
them.
"""

from __future__ import annotations

import json
import socket
import threading

import pytest

from merklekv_tpu.client import MerkleKVClient
from merklekv_tpu.cluster.node import ClusterNode
from merklekv_tpu.cluster.retry import RetryPolicy
from merklekv_tpu.cluster.sync import SyncManager
from merklekv_tpu.config import Config
from merklekv_tpu.native_bindings import NativeEngine, NativeServer
from merklekv_tpu.obs import tracewire
from merklekv_tpu.testing.faults import FaultInjector
from merklekv_tpu.utils.tracing import span

FAST = RetryPolicy(
    first_delay=0.01, max_delay=0.05, jitter=0.0, attempts=2,
    op_timeout=0.5, op_deadline=30.0,
)


@pytest.fixture(autouse=True)
def _clean_collector():
    tracewire.get_collector().clear()
    tracewire.set_propagation(True)
    yield
    tracewire.get_collector().clear()


# ------------------------------------------------------------------ token

def test_token_roundtrip():
    ctx = tracewire.new_context()
    tok = ctx.token()
    assert len(tok) == 39 and tok.startswith("tc=")
    back = tracewire.parse_token(tok)
    assert back == ctx


def test_parse_token_rejects_malformed():
    good = tracewire.new_context().token()
    bad = [
        "", "tc=", good[:-1], good + "0", good.replace("-", "_", 1),
        "tc=" + "g" * 16 + "-" + "0" * 16 + "-01",  # non-hex
        "tc=" + "0" * 16 + "-" + "0" * 16 + "-01",  # zero ids
        good.replace("tc=", "tx="),
    ]
    for tok in bad:
        assert tracewire.parse_token(tok) is None, tok


def test_child_keeps_trace_id():
    ctx = tracewire.new_context()
    child = ctx.child()
    assert child.trace_id == ctx.trace_id
    assert child.span_id != ctx.span_id


# -------------------------------------------------------------- collector

def test_collector_ring_and_wire_dump():
    col = tracewire.SpanCollector(capacity=16)
    for i in range(40):
        col.record(trace_id=1, span_id=i + 1, parent_id=0,
                   name=f"s{i}", role="initiator", ts_ns=i, dur_ns=1)
    assert len(col) == 16
    dump = col.wire_dump(0)
    assert dump.startswith("SPANS 16\r\n") and dump.endswith("END\r\n")
    # Newest-n selection keeps the tail.
    assert "name=s39" in col.wire_dump(1)
    assert col.wire_dump(1).startswith("SPANS 1\r\n")


def test_span_records_nested_parenting():
    ctx = tracewire.new_context()
    with tracewire.trace_scope(ctx):
        with span("outer"):
            with span("inner"):
                pass
    spans = tracewire.get_collector().spans()
    by_name = {s.name: s for s in spans}
    assert set(by_name) == {"outer", "inner"}
    assert by_name["outer"].trace_id == ctx.trace_id
    assert by_name["outer"].parent_id == ctx.span_id  # trace root
    assert by_name["inner"].parent_id == by_name["outer"].span_id


def test_span_records_nothing_untraced():
    with span("plain"):
        pass
    assert len(tracewire.get_collector()) == 0


# ---------------------------------------------------------------- assembly

def _rows_of(spans):
    return [
        dict(
            trace=f"{s.trace_id:016x}", span=f"{s.span_id:016x}",
            parent=f"{s.parent_id:016x}", name=s.name, role=s.role,
            ts_ns=str(s.ts_ns), dur_ns=str(s.dur_ns),
            node=s.node or "-", cycle=str(s.cycle),
        )
        for s in spans
    ]


def test_orphans_flagged_never_misparented():
    tid = 7
    rows = _rows_of([
        tracewire.SpanRecord(tid, 1, tid, "root-child", "initiator", 10, 5),
        tracewire.SpanRecord(tid, 2, 1, "child", "donor", 11, 2),
        tracewire.SpanRecord(tid, 3, 999, "lost-parent", "donor", 12, 2),
    ])
    traces = tracewire.stitch([("n1", rows)])
    spans = traces[tid]
    orphans = tracewire.orphan_spans(spans)
    assert orphans == {3}
    doc = tracewire.chrome_trace_events(traces)
    by_span = {
        e["args"]["span_id"]: e
        for e in doc["traceEvents"]
        if e.get("ph") == "X"
    }
    assert by_span[f"{3:016x}"]["args"]["orphan"] is True
    # The orphan keeps its ORIGINAL (absent) parent id — never re-pointed.
    assert by_span[f"{3:016x}"]["args"]["parent"] == f"{999:016x}"
    assert "orphan" not in by_span[f"{1:016x}"]["args"]
    assert "orphan" not in by_span[f"{2:016x}"]["args"]


def test_stitch_dedupes_and_skips_malformed():
    s = tracewire.SpanRecord(5, 1, 5, "a", "initiator", 1, 1)
    rows = _rows_of([s])
    garbage = [{"trace": "zz", "span": "1"}, {"name": "no-ids"}]
    traces = tracewire.stitch(
        [("n1", rows + garbage), ("n2", rows)]  # duplicate span from n2
    )
    assert len(traces[5]) == 1


# ------------------------------------------------------- wire integration

@pytest.fixture
def donor_pair():
    """Two donor nodes (cluster plane attached) + their engines."""
    made = []
    for _ in range(2):
        eng = NativeEngine("mem")
        srv = NativeServer(eng, "127.0.0.1", 0)
        srv.start()
        cfg = Config()
        cfg.anti_entropy.engine = "cpu"
        node = ClusterNode(cfg, eng, srv)
        node.start()
        made.append((eng, srv, node))
    yield made
    for eng, srv, node in reversed(made):
        node.stop()
        srv.close()
        eng.close()


def test_three_node_cycle_stitches_both_peers(donor_pair):
    """Acceptance: one multi-peer anti-entropy cycle yields ONE trace id
    carrying initiator spans AND donor serve spans from BOTH peers, and
    the assembled document is valid Chrome trace JSON."""
    (eng_a, srv_a, _na), (eng_b, srv_b, _nb) = donor_pair
    eng_i = NativeEngine("mem")
    try:
        for i in range(50):
            eng_a.set(b"t3:%04d" % i, b"va-%d" % i)
            eng_b.set(b"t3:%04d" % i, b"vb-newer-%d" % i)
        mgr = SyncManager(eng_i, device="cpu", retry=FAST)
        report = mgr.sync_multi(
            [f"127.0.0.1:{srv_a.port}", f"127.0.0.1:{srv_b.port}"]
        )
        assert report.union_keys == 50

        # Stitch exactly as the CLI does: TRACEDUMP over the wire from
        # both donors (they share this process's collector; stitch
        # dedupes), newest trace = this cycle.
        dumps = []
        for port in (srv_a.port, srv_b.port):
            with MerkleKVClient("127.0.0.1", port) as c:
                dumps.append((f"127.0.0.1:{port}", c.trace_dump(0)))
        traces = tracewire.stitch(dumps)
        assert traces, "no traces collected"
        tid, spans = max(
            traces.items(), key=lambda kv: max(s.ts_ns for s in kv[1])
        )
        roles = {s.role for s in spans}
        assert "initiator" in roles and "donor" in roles
        donor_nodes = {s.node for s in spans if s.role == "donor"}
        assert donor_nodes == {
            f"127.0.0.1:{srv_a.port}", f"127.0.0.1:{srv_b.port}"
        }
        assert tracewire.orphan_spans(spans) == set()
        # Perfetto-loadable: serializable, complete events, pid metadata.
        doc = tracewire.chrome_trace_events({tid: spans})
        payload = json.loads(json.dumps(doc))
        assert payload["traceEvents"]
        phases = {e["ph"] for e in payload["traceEvents"]}
        assert phases <= {"X", "M"}
        names = {e["name"] for e in payload["traceEvents"]}
        assert "serve.leafhashes" in names
    finally:
        eng_i.close()


def test_trace_cli_writes_chrome_json(donor_pair, tmp_path):
    (eng_a, srv_a, _na), (eng_b, srv_b, _nb) = donor_pair
    eng_i = NativeEngine("mem")
    try:
        for i in range(20):
            eng_a.set(b"cli:%03d" % i, b"x")
        mgr = SyncManager(eng_i, device="cpu", retry=FAST)
        mgr.sync_once("127.0.0.1", srv_a.port)
        out = tmp_path / "trace.json"
        rc = tracewire.main([
            "--nodes",
            f"127.0.0.1:{srv_a.port},127.0.0.1:{srv_b.port}",
            "--cycles", "1",
            "--out", str(out),
        ])
        assert rc == 0
        doc = json.loads(out.read_text())
        assert doc["traceEvents"]
    finally:
        eng_i.close()


def test_tracedump_without_cluster_plane_is_empty():
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0)
    srv.start()
    try:
        with MerkleKVClient("127.0.0.1", srv.port) as c:
            assert c.trace_dump() == []
    finally:
        srv.close()
        eng.close()


# ------------------------------------------------------ faults / fallback

def _stitch_local():
    spans = tracewire.get_collector().spans()
    return tracewire.stitch([("local", _rows_of(spans))])


@pytest.mark.parametrize("faults", [
    dict(drop_rate=0.08),
    dict(truncate_rate=0.08),
    dict(reorder_rate=0.25, delay=(0.001, 0.003)),
])
def test_traced_sync_stitching_survives_faults(faults):
    """Chaos: drop/truncate/reorder on a traced pairwise cycle must never
    corrupt stitching — spans either parent under a present span / the
    trace root, or are FLAGGED orphans; a span never dangles under a
    wrong parent, and assembly never raises."""
    local = NativeEngine("mem")
    remote = NativeEngine("mem")
    srv = NativeServer(remote, "127.0.0.1", 0)
    srv.start()
    cfg = Config()
    cfg.anti_entropy.engine = "cpu"
    node = ClusterNode(cfg, remote, srv)
    node.start()
    inj = FaultInjector("127.0.0.1", srv.port, seed=1234)
    inj.set_faults("both", **faults)
    try:
        for i in range(300):
            remote.set(b"f:%05d" % i, b"fresh-%d" % i)
            if i % 3:
                local.set(b"f:%05d" % i, b"stale")
        # Bounded cycles under a TIGHT deadline (convergence under faults
        # is test_faults.py's job; THIS test's bar is stitch integrity):
        # a reordered stream desyncs the protocol and burns op timeouts
        # per cycle, so a converge-or-bust loop would take minutes.
        tight = RetryPolicy(
            first_delay=0.01, max_delay=0.05, jitter=0.0, attempts=2,
            op_timeout=0.25, op_deadline=3.0,
        )
        mgr = SyncManager(
            local, device="cpu", retry=tight, hash_page=32, mget_batch=16
        )
        for _ in range(8):
            try:
                mgr.sync_once(inj.host, inj.port)
            except Exception:
                continue
            if local.merkle_root() == remote.merkle_root():
                break
        traces = _stitch_local()
        assert traces, "no spans recorded under faults"
        for tid, spans in traces.items():
            ids = {s.span_id for s in spans}
            orphans = tracewire.orphan_spans(spans)
            for s in spans:
                assert s.trace_id == tid
                assert s.span_id != s.parent_id
                ok_parent = (
                    s.parent_id == tid  # trace root
                    or s.parent_id in ids
                    or s.span_id in orphans
                )
                assert ok_parent, (s.name, s.role)
            # Assembly never raises and flags exactly the orphans.
            doc = tracewire.chrome_trace_events({tid: spans})
            flagged = {
                int(e["args"]["span_id"], 16)
                for e in doc["traceEvents"]
                if e.get("ph") == "X" and e["args"].get("orphan")
            }
            assert flagged == orphans
    finally:
        inj.close()
        node.stop()
        srv.close()
        local.close()
        remote.close()


class _OldPeer:
    """Canned pre-tracing server: rejects a 4th TREELEVEL token with the
    old parser's arity error, serves the plain form — and records every
    request line so the test can assert what actually hit the wire."""

    def __init__(self) -> None:
        self.lines: list[str] = []
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(4)
        self.port = self._srv.getsockname()[1]
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self) -> None:
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn: socket.socket) -> None:
        buf = b""
        with conn:
            while True:
                try:
                    data = conn.recv(4096)
                except OSError:
                    return
                if not data:
                    return
                buf += data
                while b"\n" in buf:
                    line, _, buf = buf.partition(b"\n")
                    text = line.decode().strip()
                    self.lines.append(text)
                    toks = text.split()
                    if toks and toks[0] == "TREELEVEL":
                        if len(toks) != 4:
                            resp = ("ERROR TREELEVEL requires arguments: "
                                    "<level> <lo> <hi>\r\n")
                        else:
                            resp = "NODES 0 5\r\n"
                    elif toks and toks[0] == "LEAFHASHES":
                        # Old parser: ONE optional arg = prefix. A token
                        # here would silently filter to the tc= prefix —
                        # the exact hazard the settled-capability rule
                        # prevents; answer per old semantics.
                        resp = "HASHES 0\r\n"
                    else:
                        resp = "ERROR Unknown command\r\n"
                    conn.sendall(resp.encode())

    def close(self) -> None:
        self._srv.close()


def test_capability_fallback_against_untraced_peer():
    peer = _OldPeer()
    try:
        c = MerkleKVClient("127.0.0.1", peer.port, timeout=2.0)
        c.trace_provider = tracewire.current_token
        c.connect()
        with tracewire.trace_scope(tracewire.new_context()):
            rows, n = c.tree_level(0, 0, 0)
            assert (rows, n) == ([], 5)
            assert c._peer_traced is False
            # Second traced verb goes straight to the plain form.
            rows, n = c.tree_level(0, 0, 0)
            assert (rows, n) == ([], 5)
            # LEAFHASHES never carries a token on an unproven (or
            # fallen-back) connection.
            assert c.leaf_hashes_ts() == {}
        c.close()
        treelevels = [ln for ln in peer.lines if ln.startswith("TREELEVEL")]
        assert len(treelevels) == 3  # traced try + plain retry + plain
        assert sum("tc=" in ln for ln in treelevels) == 1
        leaf = [ln for ln in peer.lines if ln.startswith("LEAFHASHES")]
        assert leaf == ["LEAFHASHES"]
    finally:
        peer.close()


def test_leafhashes_token_attaches_only_after_settled():
    """On a NEW server the walk settles capability via TREELEVEL, after
    which LEAFHASHES carries the token too."""
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0)
    srv.start()
    cfg = Config()
    cfg.anti_entropy.engine = "cpu"
    node = ClusterNode(cfg, eng, srv)
    node.start()
    try:
        eng.set(b"k", b"v")
        c = MerkleKVClient("127.0.0.1", srv.port, timeout=2.0)
        c.trace_provider = tracewire.current_token
        c.connect()
        with tracewire.trace_scope(tracewire.new_context()):
            c.leaf_hashes_ts()  # unsettled: plain form, no donor span
            assert c._peer_traced is None
            c.tree_level(0, 0, 0)  # settles capability
            assert c._peer_traced is True
            c.leaf_hashes_ts()  # now traced
        c.close()
        names = [s.name for s in tracewire.get_collector().spans()]
        assert names.count("serve.leafhashes") == 1
        assert "serve.treelevel" in names
    finally:
        node.stop()
        srv.close()
        eng.close()


def test_propagation_off_sends_no_tokens(donor_pair):
    (eng_a, srv_a, _na), _ = donor_pair
    eng_i = NativeEngine("mem")
    tracewire.set_propagation(False)
    try:
        for i in range(10):
            eng_a.set(b"off:%03d" % i, b"x")
        mgr = SyncManager(eng_i, device="cpu", retry=FAST)
        mgr.sync_once("127.0.0.1", srv_a.port)
        assert len(tracewire.get_collector()) == 0
    finally:
        tracewire.set_propagation(True)
        eng_i.close()

"""Pallas SHA-256 kernels.

The kernel MATH (`_compress_tiles`, plane packing) is golden-tested against
hashlib here on any backend as pure jnp. The compiled kernels themselves
only run on a real TPU — the Pallas interpreter's cost explodes past ~32
unrolled rounds, so kernel-level tests are gated on backend=="tpu" (the
driver's bench also cross-checks the kernel root against the CPU golden core
on every TPU run).
"""

import hashlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from merklekv_tpu.merkle.cpu import build_levels
from merklekv_tpu.merkle.encoding import leaf_hash
from merklekv_tpu.merkle.packing import pack_leaves
from merklekv_tpu.ops.sha256 import _IV, digest_to_bytes
from merklekv_tpu.ops.sha256_pallas import (
    TILE_M,
    _compress_tiles,
    _from_planes,
    _iv_tiles,
    _to_planes,
    build_levels_pallas,
    leaf_digests_pallas,
    tree_root_pallas,
)

on_tpu = pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="compiled pallas kernels need TPU"
)


def _hashlib_rows(msgs):
    return np.stack(
        [np.frombuffer(hashlib.sha256(m).digest(), ">u4").astype(np.uint32)
         for m in msgs]
    )


# ----------------------------------------------------- kernel math (any backend)

def test_compress_tiles_matches_hashlib():
    """One compression on a [8, 128] tile of distinct single-block messages."""
    rng = np.random.RandomState(0)
    n = 8 * 128
    msgs = [rng.bytes(32) for _ in range(n)]
    # Build padded blocks: 32-byte message -> 0x80, bitlen=256.
    words = np.zeros((16, n), np.uint32)
    for i, m in enumerate(msgs):
        w = np.frombuffer(m + b"\x80" + b"\x00" * 23 + (256).to_bytes(8, "big"),
                          ">u4").astype(np.uint32)
        words[:, i] = w
    tiles = [jnp.asarray(words[i].reshape(8, 128)) for i in range(16)]
    state = _compress_tiles(_iv_tiles((8, 128)), tiles)
    got = np.stack([np.asarray(s) for s in state]).reshape(8, n).T
    np.testing.assert_array_equal(got, _hashlib_rows(msgs))


def test_compress_tiles_chaining_two_blocks():
    """Two-block message: compress twice, compare against hashlib."""
    msg = bytes(range(100))  # 100 bytes -> 2 blocks
    padded = msg + b"\x80" + b"\x00" * 19 + (800).to_bytes(8, "big")
    assert len(padded) == 128
    w = np.frombuffer(padded, ">u4").astype(np.uint32)
    shape = (8, 128)
    state = _iv_tiles(shape)
    for b in range(2):
        tiles = [jnp.full(shape, w[b * 16 + i], jnp.uint32) for i in range(16)]
        state = _compress_tiles(state, tiles)
    got = np.stack([np.asarray(s)[0, 0] for s in state])
    expect = np.frombuffer(hashlib.sha256(msg).digest(), ">u4").astype(np.uint32)
    np.testing.assert_array_equal(got, expect)


def test_plane_roundtrip():
    rng = np.random.RandomState(1)
    rows = rng.randint(0, 2**32, (2 * TILE_M, 8), dtype=np.uint64).astype(
        np.uint32
    )
    back = np.asarray(_from_planes(_to_planes(jnp.asarray(rows))))
    np.testing.assert_array_equal(back, rows)


def test_iv_tiles_match_spec():
    tiles = _iv_tiles((8, 128))
    got = np.stack([np.asarray(t)[0, 0] for t in tiles])
    np.testing.assert_array_equal(got, _IV)


# ----------------------------------------------------- compiled kernels (TPU)

@on_tpu
def test_leaf_kernel_vs_hashlib_tpu():
    keys = [f"pk{i:04d}".encode() for i in range(300)]
    values = [b"v%d" % (i * 7) for i in range(300)]
    packed = pack_leaves(keys, values)
    got = np.asarray(leaf_digests_pallas(packed.blocks, packed.nblocks))
    expect = np.stack(
        [np.frombuffer(leaf_hash(k, v), ">u4").astype(np.uint32)
         for k, v in zip(keys, values)]
    )
    np.testing.assert_array_equal(got, expect)


@on_tpu
def test_multi_block_masking_tpu():
    keys = [b"k" * (1 + (i % 3)) for i in range(50)]
    values = [b"x" * (i * 17 % 200) for i in range(50)]
    packed = pack_leaves(keys, values)
    assert packed.max_blocks >= 2
    got = np.asarray(leaf_digests_pallas(packed.blocks, packed.nblocks))
    hl = _hashlib_rows(
        [len(k).to_bytes(4, "big") + k + len(v).to_bytes(4, "big") + v
         for k, v in zip(keys, values)]
    )
    np.testing.assert_array_equal(got, hl)


@on_tpu
@pytest.mark.parametrize("n", [1, 2, 97, 3001])
def test_tree_root_matches_cpu_tpu(n):
    items = [(f"tk{i:05d}", f"tv{i}") for i in range(n)]
    packed = pack_leaves([k.encode() for k, _ in items],
                         [v.encode() for _, v in items])
    leaves = leaf_digests_pallas(packed.blocks, packed.nblocks)
    root = np.asarray(tree_root_pallas(leaves))
    expect = build_levels([leaf_hash(k, v) for k, v in items])[-1][0]
    assert digest_to_bytes(root) == expect


@on_tpu
def test_build_levels_matches_scan_path_tpu():
    from merklekv_tpu.merkle.jax_engine import build_levels_device

    rng = np.random.RandomState(11)
    leaves = rng.randint(0, 2**32, (4097, 8), dtype=np.uint64).astype(np.uint32)
    got = build_levels_pallas(leaves)
    expect = build_levels_device(leaves)
    assert len(got) == len(expect)
    for g, e in zip(got, expect):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(e))

"""Full-process integration: spawned servers + broker, like the reference's
tests/integration harness (conftest.py spawns the cargo binary and polls the
port; test_replication.py points multiple server processes at a broker).

Here: real `python -m merklekv_tpu` processes, a real
`python -m merklekv_tpu.broker` process, TOML config files, TCP clients.
"""

import os
import socket
import subprocess
import sys
import tempfile
import time

import pytest

from merklekv_tpu.client import MerkleKVClient

pytestmark = pytest.mark.integration

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(args, **kw):
    # Server processes must not race for the single tunneled TPU; the device
    # Merkle mirror inside each server runs jax-on-CPU instead.
    env = dict(os.environ, PYTHONPATH=REPO, MERKLEKV_JAX_PLATFORM="cpu")
    return subprocess.Popen(
        [sys.executable, *args],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
        **kw,
    )


def _port_from(proc) -> int:
    line = proc.stdout.readline()
    assert "listening on" in line, f"unexpected startup line: {line!r}"
    return int(line.rsplit(":", 1)[1].split()[0])


def _wait_port(port, timeout=10):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=1).close()
            return
        except OSError:
            time.sleep(0.05)
    raise TimeoutError(f"port {port} never came up")


@pytest.fixture
def cluster(tmp_path):
    """Broker + two replicating server processes (TOML-configured)."""
    procs = []
    broker = _spawn(["-m", "merklekv_tpu.broker", "--port", "0"])
    procs.append(broker)
    broker_port = _port_from(broker)

    ports = []
    for i in (1, 2):
        cfg = tmp_path / f"node{i}.toml"
        cfg.write_text(
            f"""
host = "127.0.0.1"
port = 0
engine = "mem"

[replication]
enabled = true
mqtt_broker = "127.0.0.1"
mqtt_port = {broker_port}
topic_prefix = "itest"
client_id = "node-{i}"
"""
        )
        p = _spawn(["-m", "merklekv_tpu", "--config", str(cfg)])
        procs.append(p)
        port = _port_from(p)
        _wait_port(port)
        ports.append(port)

    yield ports
    for p in procs:
        p.terminate()
    for p in procs:
        try:
            p.wait(timeout=5)
        except subprocess.TimeoutExpired:
            p.kill()
        out = p.stdout.read()
        if out.strip():
            print(f"--- proc output ---\n{out}")


def test_cross_process_replication(cluster):
    p1, p2 = cluster
    with MerkleKVClient("127.0.0.1", p1) as c1, MerkleKVClient(
        "127.0.0.1", p2
    ) as c2:
        c1.set("xp", "hello")
        deadline = time.time() + 10
        while time.time() < deadline:
            if c2.get("xp") == "hello":
                break
            time.sleep(0.05)
        assert c2.get("xp") == "hello"

        c2.increment("shared-ctr", 3)
        deadline = time.time() + 10
        while time.time() < deadline:
            if c1.get("shared-ctr") == "3":
                break
            time.sleep(0.05)
        assert c1.get("shared-ctr") == "3"

        # Roots converge across processes.
        deadline = time.time() + 10
        while time.time() < deadline:
            if c1.hash() == c2.hash():
                break
            time.sleep(0.05)
        assert c1.hash() == c2.hash()


def test_cross_process_sync_command(cluster):
    p1, p2 = cluster
    with MerkleKVClient("127.0.0.1", p1) as c1, MerkleKVClient(
        "127.0.0.1", p2
    ) as c2:
        # Disable replication on both so only SYNC moves data.
        c1.replicate("disable")
        c2.replicate("disable")
        c1.set("only1", "v1")
        assert c2.get("only1") is None
        assert c2.sync_with("127.0.0.1", p1)
        assert c2.get("only1") == "v1"
        assert c1.hash() == c2.hash()


def test_three_process_multi_peer_convergence(tmp_path):
    """3 server processes with the fused multi-peer anti-entropy loop:
    disjoint writes converge to one root within a couple of cycles."""
    procs, ports = [], []
    try:
        # Start all three first to learn their ports (port 0), then restart
        # is avoided by passing peers via a second wave: instead, spawn on
        # fixed free ports chosen up front.
        import socket as s

        fixed = []
        socks = []
        for _ in range(3):
            sk = s.socket()
            sk.bind(("127.0.0.1", 0))
            fixed.append(sk.getsockname()[1])
            socks.append(sk)
        for sk in socks:
            sk.close()
        for i in range(3):
            peers = [f'"127.0.0.1:{fixed[j]}"' for j in range(3) if j != i]
            cfg = tmp_path / f"m{i}.toml"
            cfg.write_text(
                f"""
host = "127.0.0.1"
port = {fixed[i]}
engine = "mem"

[anti_entropy]
enabled = true
interval_seconds = 0.3
multi_peer = true
engine = "cpu"
peers = [{", ".join(peers)}]
"""
            )
            p = _spawn(["-m", "merklekv_tpu", "--config", str(cfg)])
            procs.append(p)
            _port_from(p)
            _wait_port(fixed[i])
            ports.append(fixed[i])

        clients = [MerkleKVClient("127.0.0.1", pt).connect() for pt in ports]
        try:
            for i in range(30):
                clients[i % 3].set(f"mp{i:03d}", f"v{i}")
            deadline = time.time() + 20
            while time.time() < deadline:
                roots = {c.hash() for c in clients}
                if len(roots) == 1 and clients[0].dbsize() == 30:
                    break
                time.sleep(0.1)
            assert len({c.hash() for c in clients}) == 1
            for c in clients:
                assert c.dbsize() == 30
        finally:
            for c in clients:
                c.close()
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


def test_persistence_across_restart(tmp_path):
    data = tmp_path / "data"
    p = _spawn(
        ["-m", "merklekv_tpu", "--port", "0", "--engine", "log",
         "--storage-path", str(data)]
    )
    port = _port_from(p)
    _wait_port(port)
    with MerkleKVClient("127.0.0.1", port) as c:
        c.set("durable", "state")
        c.shutdown()
    p.wait(timeout=10)

    p2 = _spawn(
        ["-m", "merklekv_tpu", "--port", "0", "--engine", "log",
         "--storage-path", str(data)]
    )
    port2 = _port_from(p2)
    _wait_port(port2)
    try:
        with MerkleKVClient("127.0.0.1", port2) as c:
            assert c.get("durable") == "state"
    finally:
        p2.terminate()
        p2.wait(timeout=5)


def test_crash_recovery_prefix_consistency(tmp_path):
    """SIGKILL while writes are still streaming, then restart on the log.

    The durable engine appends each record with a raw write() BEFORE the
    server sends OK, so under a hard process kill (no SHUTDOWN, no flush)
    every ACKNOWLEDGED write must survive replay; an un-acked in-flight
    record may or may not land. Recovery must also be write-order
    contiguous — nothing corrupted, reordered, or resurrected."""
    import threading

    data = tmp_path / "data"
    p = _spawn(
        ["-m", "merklekv_tpu", "--port", "0", "--engine", "log",
         "--storage-path", str(data)]
    )
    port = _port_from(p)
    _wait_port(port)
    acked = 0
    done = threading.Event()

    def writer():
        nonlocal acked
        try:
            with MerkleKVClient("127.0.0.1", port) as c:
                for i in range(100_000):
                    c.set(f"cr:{i:06d}", f"val-{i}")
                    acked += 1
        except Exception:
            pass  # connection dies at the kill — expected
        finally:
            done.set()

    from merklekv_tpu.testing.faults import PeerProcessKiller

    t = threading.Thread(target=writer)
    t.start()
    # SIGKILL mid-stream: no shutdown path, no engine close.
    killer = PeerProcessKiller(p)
    killed = killer.kill_when(lambda: acked >= 200, timeout=10)
    done.wait(timeout=10)
    t.join(timeout=10)
    assert killed, f"writer only got {acked} acks before the deadline"

    p2 = _spawn(
        ["-m", "merklekv_tpu", "--port", "0", "--engine", "log",
         "--storage-path", str(data)]
    )
    port2 = _port_from(p2)
    _wait_port(port2)
    try:
        with MerkleKVClient("127.0.0.1", port2) as c:
            keys = c.scan("cr:")
            recovered = {k: c.get(k) for k in keys}
        # Every acked write survived (ack implies the record hit the fd).
        assert len(recovered) >= acked, (len(recovered), acked)
        # Values exact.
        for k, v in recovered.items():
            i = int(k.split(":")[1])
            assert v == f"val-{i}", (k, v)
        # Write-order contiguity: indices are exactly 0..len-1 (at most
        # one un-acked in-flight record beyond the acked prefix).
        idxs = sorted(int(k.split(":")[1]) for k in recovered)
        assert idxs == list(range(len(idxs))), (
            f"recovery gap: {len(idxs)} keys, max {idxs[-1] if idxs else None}"
        )
        assert len(idxs) <= acked + 1
    finally:
        p2.terminate()
        p2.wait(timeout=5)

"""Compile and run the C++ header-only client against the embedded server."""

import os
import subprocess
import tempfile

import pytest

from merklekv_tpu.native_bindings import NativeEngine, NativeServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DRIVER = r"""
#include "merklekv_client.hpp"
#include <cassert>
#include <cstdio>
#include <cstdlib>

int main(int argc, char** argv) {
  mkvclient::Client c("127.0.0.1", uint16_t(std::atoi(argv[1])));
  c.set("cppk", "cppv with spaces");
  auto v = c.get("cppk");
  assert(v && *v == "cppv with spaces");
  assert(!c.get("missing"));
  assert(c.increment("n", 5) == 5);
  assert(c.decrement("n", 2) == 3);
  assert(c.append("s", "ab") == "ab");
  assert(c.prepend("s", "x") == "xab");
  auto keys = c.scan();
  assert(keys.size() == 3);
  assert(c.dbsize() == 3);
  assert(c.hash().size() == 64);
  assert(c.ping());
  assert(c.echo("hello") == "hello");
  assert(c.stats().count("total_commands") == 1);
  (void)c.metrics();  // empty block on a bare server; must round-trip
  auto out = c.pipeline({"SET p1 a", "SET p2 b", "GET p1"});
  assert(out[0] == "OK" && out[2] == "VALUE a");
  bool threw = false;
  try { c.request("NOSUCH x"); } catch (const mkvclient::ProtocolError&) { threw = true; }
  assert(threw);
  assert(c.del("cppk"));
  assert(!c.del("cppk"));
  std::puts("CPP CLIENT OK");
  return 0;
}
"""


@pytest.fixture(scope="module")
def driver_bin():
    d = tempfile.mkdtemp()
    src = os.path.join(d, "driver.cc")
    out = os.path.join(d, "driver")
    with open(src, "w") as f:
        f.write(DRIVER)
    subprocess.run(
        ["g++", "-std=c++17", "-O1", "-Wall",
         "-I", os.path.join(REPO, "clients", "cpp"), src, "-o", out],
        check=True, capture_output=True,
    )
    return out


def test_cpp_client_end_to_end(driver_bin):
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0)
    srv.start()
    try:
        r = subprocess.run(
            [driver_bin, str(srv.port)], capture_output=True, text=True,
            timeout=30,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "CPP CLIENT OK" in r.stdout
    finally:
        srv.close()
        eng.close()

"""Device-resident Merkle mirror behind the serving HASH path.

Round-1 gap (VERDICT): the TPU incremental tree existed but nothing served
from it — HASH recomputed a full CPU root per call. These tests pin:
  - HASH parity between the device mirror and the native CPU path,
  - incremental (not full-rebuild) absorption of value updates,
  - truncate invalidation,
  - remote LWW applies feeding the mirror.
"""

import time
import uuid

import pytest

from merklekv_tpu.client import MerkleKVClient
from merklekv_tpu.cluster.mirror import DeviceTreeMirror
from merklekv_tpu.cluster.node import ClusterNode
from merklekv_tpu.cluster.transport import TcpBroker
from merklekv_tpu.config import Config
from merklekv_tpu.native_bindings import NativeEngine, NativeServer


@pytest.fixture(scope="module", autouse=True)
def _prewarm_jax():
    """First-use JAX compile of the device tree takes seconds under full-suite
    load; warm it once so client calls inside tests never absorb that cost
    (the historical flake: a 5 s client timeout racing the warm thread)."""
    from merklekv_tpu.merkle.incremental import DeviceMerkleState

    st = DeviceMerkleState.from_items([(b"warm", b"up")])
    st.apply([(b"warm", b"again")])
    _ = st.root_hex()


@pytest.fixture
def broker():
    b = TcpBroker()
    yield b
    b.close()


class Node:
    def __init__(self, broker, topic, node_id):
        self.engine = NativeEngine("mem")
        self.server = NativeServer(self.engine, "127.0.0.1", 0)
        self.server.start()
        cfg = Config()
        cfg.replication.enabled = True
        cfg.replication.mqtt_broker = broker.host
        cfg.replication.mqtt_port = broker.port
        cfg.replication.topic_prefix = topic
        cfg.replication.client_id = node_id
        self.cluster = ClusterNode(cfg, self.engine, self.server)
        self.cluster.start()
        self.client = MerkleKVClient(
            "127.0.0.1", self.server.port, timeout=30.0
        ).connect()

    def close(self):
        self.client.close()
        self.cluster.stop()
        self.server.close()
        self.engine.close()


@pytest.fixture
def node(broker):
    n = Node(broker, f"mirror-{uuid.uuid4().hex[:8]}", "m1")
    yield n
    n.close()


def _wait_ready(node, timeout=30.0):
    node.client.hash()  # triggers warming
    deadline = time.time() + timeout
    while time.time() < deadline:
        if node.cluster._mirror is not None and node.cluster._mirror.ready():
            return
        time.sleep(0.02)
    raise TimeoutError("mirror never warmed")


def test_hash_served_from_device_matches_native(node):
    for i in range(32):
        node.client.set(f"mk{i:03d}", f"v{i}")
    native_root = node.engine.merkle_root().hex()
    assert node.client.hash() == native_root  # native path while cold
    _wait_ready(node)
    # Warm path must agree bit-exactly with the native CPU tree.
    assert node.cluster.device_root_hex() == native_root
    assert node.client.hash() == native_root


def test_value_updates_are_incremental_after_warm(node):
    for i in range(64):
        node.client.set(f"ik{i:03d}", f"v{i}")
    _wait_ready(node)
    node.client.hash()  # force initial build
    state = node.cluster._mirror.state
    rebuilds_before = state.full_rebuilds
    # Value updates of existing keys: incremental scatter path only.
    for i in range(8):
        node.client.set(f"ik{i:03d}", f"updated-{i}")
    # force=True: drain the write stream through the pump first — the
    # unforced path serves the last-published snapshot (bounded staleness).
    root = node.cluster.device_root_hex(force=True)
    assert root == node.engine.merkle_root().hex()
    assert state.full_rebuilds == rebuilds_before
    assert state.incremental_batches >= 1


def test_truncate_invalidates_mirror(node):
    node.client.set("gone", "soon")
    _wait_ready(node)
    assert node.cluster.device_root_hex(force=True) != "0" * 64
    node.client.flushdb()
    assert node.cluster.device_root_hex(force=True) == "0" * 64
    assert node.client.hash() == "0" * 64


def test_remote_applies_feed_mirror(broker):
    topic = f"mirror2-{uuid.uuid4().hex[:8]}"
    n1 = Node(broker, topic, "r1")
    n2 = Node(broker, topic, "r2")
    try:
        _wait_ready(n2)
        n1.client.set("replicated", "value")
        deadline = time.time() + 10
        while time.time() < deadline:
            if n2.client.get("replicated") == "value":
                break
            time.sleep(0.02)
        assert n2.client.get("replicated") == "value"
        # n2's device root includes the remotely applied write (force
        # publishes the staged frame; the unforced path trails by at most
        # the staleness window).
        assert (
            n2.cluster.device_root_hex(force=True)
            == n2.engine.merkle_root().hex()
        )
    finally:
        n1.close()
        n2.close()


def test_sync_repairs_feed_mirror(broker):
    """Anti-entropy writes bypass the server event queue; the mirror must
    still see them or HASH serves a stale root forever after a SYNC."""
    topic = f"mirror3-{uuid.uuid4().hex[:8]}"
    n1 = Node(broker, topic, "s1")
    try:
        # A plain peer outside the replication fabric, with extra data.
        peer_eng = NativeEngine("mem")
        peer_srv = NativeServer(peer_eng, "127.0.0.1", 0)
        peer_srv.start()
        try:
            peer_eng.set(b"sync-only", b"via-anti-entropy")
            n1.client.set("own", "write")
            _wait_ready(n1)
            assert (
                n1.cluster.device_root_hex(force=True)
                == n1.engine.merkle_root().hex()
            )
            # SYNC pulls sync-only in through the engine bindings.
            assert n1.client.sync_with("127.0.0.1", peer_srv.port)
            assert n1.client.get("sync-only") == "via-anti-entropy"
            # The warm mirror must reflect the repair after a pump drain.
            assert (
                n1.cluster.device_root_hex(force=True)
                == n1.engine.merkle_root().hex()
            )
        finally:
            peer_srv.close()
            peer_eng.close()
    finally:
        n1.close()


def test_mirror_converges_despite_event_payload_staleness():
    """on_events re-reads the engine, so replay order can't regress values."""
    eng = NativeEngine("mem")
    try:
        eng.set(b"k", b"newest")
        mirror = DeviceTreeMirror(eng)
        mirror.start_warming()
        deadline = time.time() + 30
        while not mirror.ready() and time.time() < deadline:
            time.sleep(0.02)
        assert mirror.ready()
        # A stale event for k arrives late: the mirror must end on the
        # engine's current value, not the payload's.
        from merklekv_tpu.cluster.change_event import ChangeEvent, OpKind

        mirror.on_events(
            [ChangeEvent(op=OpKind.SET, key="k", val=b"old", ts=1, src="x")]
        )
        assert mirror.root_hex() == eng.merkle_root().hex()
        mirror.close()
    finally:
        eng.close()

"""Asynchronous Merkle maintenance: the bounded-staleness device pump and
the version-stamped tree answers (ISSUE 11).

Pins the freshness contract end to end:
  - stamped wire forms (HASH/TREELEVEL/LEAFHASHES/HASHPAGE + vs= token)
    against the native server, including the forced-refresh flag;
  - capability fallback against pre-stamp peers (arity-error settle, the
    trace-token discipline) + a truncation/byte-flip fuzz sweep over the
    stamped TREELEVEL reply;
  - the staleness bound under a seeded write storm (pump keeps the served
    tree inside the [device] window; roots bit-identical once it closes);
  - NO synchronous replicator flush on the unforced root-serving path
    (the regression the whole issue exists to prevent);
  - pump chaos: a drain killed mid-flight invalidates cleanly and the next
    query recovers a consistent root;
  - the walk's bounded-trailing handling: clip instead of abort on stamped
    mid-walk churn, forced refresh on a deeply lagging donor, and
    convergence under an active write storm against a bounded-trailing
    donor.
"""

from __future__ import annotations

import random
import socket
import threading
import time
import uuid

import pytest

from merklekv_tpu.client import (
    MerkleKVClient,
    MerkleKVError,
    ProtocolError,
)
from merklekv_tpu.cluster.node import ClusterNode
from merklekv_tpu.cluster.retry import RetryPolicy
from merklekv_tpu.cluster.sync import SyncManager
from merklekv_tpu.cluster.transport import TcpBroker
from merklekv_tpu.config import Config
from merklekv_tpu.native_bindings import NativeEngine, NativeServer

FAST = RetryPolicy(
    first_delay=0.01, max_delay=0.05, jitter=0.0, attempts=2,
    op_timeout=2.0, op_deadline=60.0,
)


@pytest.fixture(scope="module", autouse=True)
def _prewarm_jax():
    """One-time JAX compile of the device tree (seconds under full-suite
    load) so in-test client calls never absorb it."""
    from merklekv_tpu.merkle.incremental import DeviceMerkleState

    st = DeviceMerkleState.from_items([(b"warm", b"up")])
    st.apply([(b"warm", b"again")])
    _ = st.root_hex()


@pytest.fixture
def bare():
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0)
    srv.start()
    yield eng, srv
    srv.close()
    eng.close()


# ------------------------------------------------------ stamped wire forms


def test_unstamped_forms_are_byte_identical(bare):
    """A client that never opts in sees the exact legacy wire shapes."""
    eng, srv = bare
    eng.set(b"k1", b"v1")
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        rows, n = c.tree_level(0, 0, 0)
        assert (rows, n) == ([], 1)
        assert c.last_stamp is None
        c.hash()
        assert c.last_stamp is None
        c.leaf_hashes_ts()
        assert c.last_stamp is None
        c.leaf_hashes_page(10)
        assert c.last_stamp is None


def test_stamped_answers_carry_engine_version(bare):
    eng, srv = bare
    for i in range(8):
        eng.set(f"sk{i}".encode(), b"v")
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        c.version_stamps = True
        # TREELEVEL is fail-closed: the stamp attaches unsettled and the
        # first answer settles the capability.
        rows, n = c.tree_level(0, 0, 0)
        assert n == 8 and c._peer_stamped is True
        assert c.last_stamp is not None
        ver, lag = c.last_stamp
        assert ver == eng.version() and lag == 0
        # Live-engine verbs: stamp == current engine version, lag 0.
        c.leaf_hashes_page(4)
        assert c.last_stamp == (eng.version(), 0)
        c.leaf_hashes_ts()
        assert c.last_stamp == (eng.version(), 0)
        root = c.hash()
        assert root == eng.merkle_root().hex()
        assert c.last_stamp == (eng.version(), 0)


def test_treelevel_force_overrides_serve_stale_ttl(bare):
    """The native host tree serves one consistent build for a 5 s TTL; a
    vs=03 forced refresh rebuilds to the live engine immediately (the
    walk's escalation path, and the exactness escape hatch)."""
    eng, srv = bare
    for i in range(5):
        eng.set(f"fk{i}".encode(), b"v")
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        c.version_stamps = True
        _, n = c.tree_level(0, 0, 0)
        assert n == 5
        built_ver = c.last_stamp[0]
        eng.set(b"fk-new", b"v")  # within the TTL: cache keeps serving
        _, n = c.tree_level(0, 0, 0)
        assert n == 5, "TTL cache must keep serving the same tree"
        ver, lag = c.last_stamp
        assert ver == built_ver and lag >= 1  # the stamp ADMITS the lag
        _, n = c.tree_level(0, 0, 0, force=True)
        assert n == 6, "forced refresh must rebuild to the live engine"
        ver, lag = c.last_stamp
        assert ver == eng.version() and lag == 0


def test_stamped_hash_tracks_writes(bare):
    eng, srv = bare
    eng.set(b"h1", b"v")
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        c.version_stamps = True
        c.tree_level(0, 0, 0)  # settle (HASH stamps only when settled)
        c.hash()
        v1 = c.last_stamp[0]
        eng.set(b"h2", b"v")
        assert c.hash() == eng.merkle_root().hex()
        assert c.last_stamp[0] > v1


# ------------------------------------------------- capability fallback


class _CannedPeer:
    """Scripted line server: TREELEVEL arity rules selectable per era."""

    def __init__(self, parses_trace: bool, parses_stamp: bool) -> None:
        self.parses_trace = parses_trace
        self.parses_stamp = parses_stamp
        self.lines: list[str] = []
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(4)
        self.port = self._srv.getsockname()[1]
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self) -> None:
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _strip(self, toks: list[str]) -> list[str]:
        if self.parses_trace and toks and toks[-1].startswith("tc="):
            toks = toks[:-1]
        if self.parses_stamp and toks and toks[-1].startswith("vs="):
            toks = toks[:-1]
        return toks

    def _handle(self, conn: socket.socket) -> None:
        buf = b""
        with conn:
            while True:
                try:
                    data = conn.recv(4096)
                except OSError:
                    return
                if not data:
                    return
                buf += data
                while b"\n" in buf:
                    line, _, buf = buf.partition(b"\n")
                    text = line.decode().strip()
                    self.lines.append(text)
                    toks = self._strip(text.split())
                    if toks and toks[0] == "TREELEVEL":
                        if len(toks) != 4:
                            resp = ("ERROR TREELEVEL requires arguments: "
                                    "<level> <lo> <hi>\r\n")
                        elif self.parses_stamp and "vs=" in text:
                            resp = "NODES 0 7 42 0\r\n"
                        else:
                            resp = "NODES 0 7\r\n"
                    else:
                        resp = "ERROR Unknown command\r\n"
                    conn.sendall(resp.encode())

    def close(self) -> None:
        self._srv.close()


def test_stamp_fallback_against_pre_stamp_pre_trace_peer():
    """An old peer rejects vs= AND tc= with arity errors: the client drops
    the stamp first, then the trace, and settles both tri-states False —
    three requests total, then straight-to-plain forever after."""
    from merklekv_tpu.obs import tracewire

    peer = _CannedPeer(parses_trace=False, parses_stamp=False)
    try:
        c = MerkleKVClient("127.0.0.1", peer.port, timeout=2.0)
        c.version_stamps = True
        c.trace_provider = tracewire.current_token
        c.connect()
        with tracewire.trace_scope(tracewire.new_context()):
            rows, n = c.tree_level(0, 0, 0)
            assert (rows, n) == ([], 7)
            assert c._peer_stamped is False and c._peer_traced is False
            assert c.last_stamp is None
            rows, n = c.tree_level(0, 0, 0)
            assert (rows, n) == ([], 7)
        c.close()
        tls = [ln for ln in peer.lines if ln.startswith("TREELEVEL")]
        # vs+tc try, tc-only retry, plain retry, then one plain call.
        assert len(tls) == 4
        assert sum("vs=" in ln for ln in tls) == 1
        assert sum("tc=" in ln for ln in tls) == 2
    finally:
        peer.close()


def test_stamp_fallback_against_trace_only_peer():
    """A one-release-back peer parses tc= but not vs=: dropping only the
    stamp keeps the trace capability settled True."""
    from merklekv_tpu.obs import tracewire

    peer = _CannedPeer(parses_trace=True, parses_stamp=False)
    try:
        c = MerkleKVClient("127.0.0.1", peer.port, timeout=2.0)
        c.version_stamps = True
        c.trace_provider = tracewire.current_token
        c.connect()
        with tracewire.trace_scope(tracewire.new_context()):
            rows, n = c.tree_level(0, 0, 0)
            assert (rows, n) == ([], 7)
            assert c._peer_stamped is False and c._peer_traced is True
        c.close()
        tls = [ln for ln in peer.lines if ln.startswith("TREELEVEL")]
        assert len(tls) == 2  # vs+tc try, tc-only success
    finally:
        peer.close()


def test_stamped_peer_answers_stamped():
    peer = _CannedPeer(parses_trace=True, parses_stamp=True)
    try:
        c = MerkleKVClient("127.0.0.1", peer.port, timeout=2.0)
        c.version_stamps = True
        c.connect()
        rows, n = c.tree_level(0, 0, 0)
        assert (rows, n) == ([], 7)
        assert c._peer_stamped is True
        assert c.last_stamp == (42, 0)
        c.close()
    finally:
        peer.close()


class _OneShotServer:
    """Answers every connection with one fixed byte blob, then closes —
    the fuzz target for reply-corruption sweeps."""

    def __init__(self, blob: bytes) -> None:
        self._blob = blob
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(4)
        self.port = self._srv.getsockname()[1]
        self._t = threading.Thread(target=self._serve, daemon=True)
        self._t.start()

    def _serve(self) -> None:
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            try:
                conn.recv(4096)
                conn.sendall(self._blob)
                conn.shutdown(socket.SHUT_WR)
            except OSError:
                pass
            finally:
                conn.close()

    def close(self) -> None:
        self._srv.close()


def test_stamped_treelevel_reply_fuzz_never_silently_wrong():
    """Truncate the stamped TREELEVEL reply at EVERY byte offset and flip
    48 seeded bytes: the client either raises a clean typed error or
    returns exactly the true rows — never a partial/garbled parse."""
    digest = "ab" * 32
    good = f"NODES 1 5 42 0\r\n0 {digest}\r\n".encode()
    true_rows = [(0, digest)]

    def attempt(blob: bytes):
        srv = _OneShotServer(blob)
        try:
            c = MerkleKVClient("127.0.0.1", srv.port, timeout=2.0)
            c.version_stamps = True
            c._peer_stamped = True  # settled: straight to the stamped form
            c.connect()
            try:
                return c.tree_level(0, 0, 1)
            finally:
                c.close()
        finally:
            srv.close()

    assert attempt(good) == (true_rows, 5)

    for cut in range(len(good)):
        try:
            out = attempt(good[:cut])
        except MerkleKVError:
            continue  # clean typed failure
        assert out == (true_rows, 5), f"truncation at {cut} mis-parsed"

    rng = random.Random(1311)
    for _ in range(48):
        pos = rng.randrange(len(good))
        flipped = bytearray(good)
        flipped[pos] ^= 1 << rng.randrange(8)
        if bytes(flipped) == good:
            continue
        try:
            rows, n = attempt(bytes(flipped))
        except MerkleKVError:
            continue
        # A flip inside a numeric field parses as a different (valid)
        # number — undetectable by construction — but any surviving rows
        # must still be well-formed 32-byte digests, never garbage that
        # happens to "parse".
        assert all(len(bytes.fromhex(h)) == 32 for _, h in rows)


def test_stamped_hash_reply_fuzz():
    """Same sweep over the stamped HASH reply: a corrupted stamp/root line
    raises or parses to a well-formed root, never desyncs."""
    root = "cd" * 32
    good = f"HASH {root} 7 0\r\n".encode()

    def attempt(blob: bytes):
        srv = _OneShotServer(blob)
        try:
            c = MerkleKVClient("127.0.0.1", srv.port, timeout=2.0)
            c.version_stamps = True
            c._peer_stamped = True
            c.connect()
            try:
                return c.hash()
            finally:
                c.close()
        finally:
            srv.close()

    assert attempt(good) == root
    for cut in range(len(good)):
        try:
            out = attempt(good[:cut])
        except MerkleKVError:
            continue
        assert out == root, f"truncation at {cut} mis-parsed: {out!r}"


# --------------------------------------------------------- pump behavior


class _Node:
    def __init__(self, broker, topic, node_id, max_staleness_ms=200.0):
        self.engine = NativeEngine("mem")
        self.server = NativeServer(self.engine, "127.0.0.1", 0)
        self.server.start()
        cfg = Config()
        cfg.replication.enabled = True
        cfg.replication.mqtt_broker = broker.host
        cfg.replication.mqtt_port = broker.port
        cfg.replication.topic_prefix = topic
        cfg.replication.client_id = node_id
        cfg.device.max_staleness_ms = max_staleness_ms
        self.cluster = ClusterNode(cfg, self.engine, self.server)
        self.cluster.start()
        self.client = MerkleKVClient(
            "127.0.0.1", self.server.port, timeout=30.0
        ).connect()

    def close(self):
        self.client.close()
        self.cluster.stop()
        self.server.close()
        self.engine.close()


@pytest.fixture
def broker():
    b = TcpBroker()
    yield b
    b.close()


@pytest.fixture
def node(broker):
    n = _Node(broker, f"pump-{uuid.uuid4().hex[:8]}", "p1")
    yield n
    n.close()


def _wait_ready(node, timeout=60.0):
    node.client.hash()  # triggers warming
    deadline = time.time() + timeout
    while time.time() < deadline:
        if node.cluster._mirror is not None and node.cluster._mirror.ready():
            return node.cluster._mirror
        time.sleep(0.02)
    raise TimeoutError("mirror never warmed")


def test_root_query_performs_no_replicator_flush(node):
    """THE acceptance invariant: no root-serving query path performs a
    synchronous replicator flush — only the explicit force path does."""
    node.client.set("nf", "v")
    _wait_ready(node)
    rep = node.cluster.replicator
    flushes = {"n": 0}
    real_flush = rep.flush

    def counting_flush():
        flushes["n"] += 1
        return real_flush()

    rep.flush = counting_flush
    try:
        node.client.hash()
        node.client.tree_level(0, 0, 1)
        node.cluster.device_root_hex()
        node.cluster.device_tree_level(0, 0, 1)
        assert flushes["n"] == 0, "unforced query path flushed the replicator"
        node.cluster.device_root_hex(force=True)
        assert flushes["n"] == 1, "force path must drain the write stream"
    finally:
        rep.flush = real_flush


def _seeded_storm_lag_samples(node) -> tuple[list[float], object]:
    """Shared rig for the staleness-bound tests: seed, warm, shake out
    the scatter-bucket kernel compiles, then sample pump lag under a
    3-second single-writer storm. Returns (lag_samples, mirror)."""
    # Seed BEFORE warming and shake out the scatter-bucket kernel compiles
    # (first use of each batch-size bucket compiles for seconds — a
    # one-time cost that would otherwise read as pump lag; the bench pays
    # the same shakeout).
    for base in range(0, 512, 64):
        node.client.mset(
            {f"st{i:04d}": "seed" for i in range(base, base + 64)}
        )
    mirror = _wait_ready(node)
    for burst in (1, 8, 24, 60, 140, 300):
        node.client.mset({f"st{i:04d}": "shake" for i in range(burst)})
        node.cluster.device_root_hex(force=True)
    rng = random.Random(2311)
    stop = threading.Event()
    lag_samples: list[float] = []

    def storm():
        i = 0
        while not stop.is_set():
            node.client.set(f"st{rng.randrange(512):04d}", f"v{i}")
            i += 1

    t = threading.Thread(target=storm, daemon=True)
    t.start()
    try:
        deadline = time.time() + 3.0
        while time.time() < deadline:
            lag_samples.append(mirror.pump_lag_ms())
            time.sleep(0.01)
    finally:
        stop.set()
        t.join(timeout=10)
    return lag_samples, mirror


@pytest.mark.slow
def test_staleness_tight_bound_under_seeded_write_storm(node):
    """The TIGHT wall contract — the configured 200 ms window with 5x
    slack. On shared/loaded CI machines the JAX dispatch jitter alone has
    been measured at ~2.9 s (identical failure on a pristine seed), so
    this calibration-sensitive bound runs in the slow tier where the
    machine is otherwise quiet; tier-1 keeps the loose invariant below."""
    lag_samples, _ = _seeded_storm_lag_samples(node)
    assert max(lag_samples) <= 5 * 200.0, f"lag exceeded: {max(lag_samples)}"


def test_staleness_bounded_under_seeded_write_storm(node):
    """Property: under a sustained write storm the pump keeps the served
    tree inside the staleness window, and once the storm stops the served
    root converges bit-identically to the engine root within the window.

    Tier-1 asserts the loose invariant — BOUNDED, with enough slack
    (25x the 200 ms window) to absorb measured scheduler/dispatch jitter
    on busy CI machines; unbounded staleness was the bug. The tight 5x
    calibration bound lives in the slow-marked sibling above."""
    lag_samples, mirror = _seeded_storm_lag_samples(node)
    assert max(lag_samples) <= 25 * 200.0, f"lag exceeded: {max(lag_samples)}"
    # Window closes -> served root == engine root, bit-identical.
    deadline = time.time() + 5.0
    engine_root = node.engine.merkle_root().hex()
    while time.time() < deadline:
        served = mirror.published_root_hex()
        if served == engine_root:
            break
        time.sleep(0.02)
    assert mirror.published_root_hex() == engine_root
    assert mirror.staleness() == 0
    # The gauge is exact: stage one more write, force-drain, still exact.
    node.client.set("st-final", "v")
    assert node.cluster.device_root_hex(force=True) == (
        node.engine.merkle_root().hex()
    )
    assert mirror.staleness() == 0


def test_pump_killed_mid_drain_recovers_consistent_root(node):
    """Chaos: the pump dies mid-drain (injected). The mirror invalidates —
    the NEXT query serves a consistent root from the native fallback — and
    a re-warm restores device serving with an exact root."""
    mirror = _wait_ready(node)
    boom = {"armed": True}

    def inject():
        if boom["armed"]:
            boom["armed"] = False
            raise RuntimeError("injected pump death")

    mirror._pump_inject = inject
    node.client.set("chaos", "v")
    # Wait for the pump to hit the injection and invalidate.
    deadline = time.time() + 10
    while time.time() < deadline and mirror.ready():
        time.sleep(0.02)
    assert not mirror.ready(), "pump death must invalidate the state"
    mirror._pump_inject = None
    # Next query: native fallback answers the CORRECT root immediately.
    assert node.client.hash() == node.engine.merkle_root().hex()
    # And the mirror re-warms back to device serving, still exact.
    deadline = time.time() + 60
    while time.time() < deadline and not mirror.ready():
        time.sleep(0.02)
    assert mirror.ready(), "mirror never re-warmed after pump death"
    assert node.cluster.device_root_hex(force=True) == (
        node.engine.merkle_root().hex()
    )


def test_tree_staleness_flight_event_one_flag_per_window():
    """A pump stalled past the window raises ONE tree_staleness flight
    event per flag window (the slow-burst discipline)."""
    from merklekv_tpu.cluster.mirror import DeviceTreeMirror
    from merklekv_tpu.obs.flightrec import get_recorder

    eng = NativeEngine("mem")
    try:
        eng.set(b"k", b"v")
        mirror = DeviceTreeMirror(eng, max_staleness_ms=20.0)
        mirror.start_warming()
        deadline = time.time() + 60
        while not mirror.ready() and time.time() < deadline:
            time.sleep(0.02)
        assert mirror.ready()
        rec = get_recorder()
        before = sum(
            1 for e in rec.last(0) if e.kind == "tree_staleness"
        )
        # Simulate a wedged pump: staged work waiting far past the window.
        with mirror._mu:
            mirror._staged_since_m = time.monotonic() - 1.0
        mirror._check_staleness_breach()
        mirror._check_staleness_breach()  # inside the flag window: no dup
        events = [e for e in rec.last(0) if e.kind == "tree_staleness"]
        assert len(events) == before + 1
        ev = events[-1]
        assert int(ev.fields["lag_ms"]) >= 900
        assert int(ev.fields["window_ms"]) == 20
        mirror.close()
    finally:
        eng.close()


def test_blackbox_flags_tree_staleness_anomaly():
    from merklekv_tpu.obs.blackbox import find_anomalies, merge_timeline
    from merklekv_tpu.obs.flightrec import FlightEvent, SpillDoc

    ev = FlightEvent(
        seq=1, wall_ns=1000, mono_ns=1000, kind="tree_staleness",
        fields={"lag_ms": 500, "lag_versions": 9000, "window_ms": 200},
    )
    doc = SpillDoc(path="x", meta={"node": "n1"}, events=[ev], samples=[])
    timeline = merge_timeline([doc])
    kinds = [a.kind for a in find_anomalies([doc], timeline)]
    assert "tree_staleness" in kinds


# ------------------------------------------- stamp-aware anti-entropy walk


@pytest.fixture
def two_nodes():
    nodes = []
    for _ in range(2):
        eng = NativeEngine("mem")
        srv = NativeServer(eng, "127.0.0.1", 0)
        srv.start()
        nodes.append((eng, srv))
    yield nodes
    for eng, srv in nodes:
        srv.close()
        eng.close()


def _fill(eng, items):
    for k, v in items.items():
        eng.set(k.encode(), v.encode())


def test_walk_clips_on_stamped_midwalk_churn(two_nodes, monkeypatch):
    """A stamped donor republishing mid-walk (leaf count moves) no longer
    aborts the walk to a full paged scan: the walker CLIPS to its verified
    frontier and repairs those intervals with key-bounded pages — and
    still converges bit-identically."""
    (leng, lsrv), (reng, rsrv) = two_nodes
    items = {f"cl{i:04d}": f"v{i}" for i in range(600)}
    _fill(reng, items)
    local = dict(items)
    for i in (7, 300, 555):
        local[f"cl{i:04d}"] = "stale"
    _fill(leng, local)

    calls = {"n": 0}
    real = MerkleKVClient.tree_level

    def lying_tree_level(self, level, lo, hi, force=False):
        rows, n = real(self, level, lo, hi, force=force)
        calls["n"] += 1
        if calls["n"] >= 3:
            # The donor republished: leaf count moved, stamp present.
            self.last_stamp = (999_999, 0)
            return rows, n + 1
        return rows, n

    monkeypatch.setattr(MerkleKVClient, "tree_level", lying_tree_level)
    mgr = SyncManager(leng, device="cpu", mode="bisect", retry=FAST)
    report = mgr.sync_once("127.0.0.1", rsrv.port)
    assert report.mode == "bisect"
    assert report.walk_clipped, report.details
    assert leng.merkle_root() == reng.merkle_root()


def test_walk_aborts_to_paging_for_unstamped_churny_donor(
    two_nodes, monkeypatch
):
    """Legacy behavior preserved: an UNSTAMPED donor whose leaf count moves
    mid-walk still degrades to the paged scan (no stamp = no way to tell
    bounded trailing from unbounded churn)."""
    (leng, lsrv), (reng, rsrv) = two_nodes
    items = {f"ab{i:04d}": f"v{i}" for i in range(400)}
    _fill(reng, items)
    local = dict(items)
    local["ab0100"] = "stale"
    _fill(leng, local)

    calls = {"n": 0}
    real = MerkleKVClient.tree_level

    def unstamped_churn(self, level, lo, hi, force=False):
        rows, n = real(self, level, lo, hi, force=force)
        self.last_stamp = None  # donor predates stamps
        calls["n"] += 1
        if calls["n"] >= 3:
            return rows, n + 1
        return rows, n

    monkeypatch.setattr(MerkleKVClient, "tree_level", unstamped_churn)
    mgr = SyncManager(leng, device="cpu", mode="bisect", retry=FAST)
    report = mgr.sync_once("127.0.0.1", rsrv.port)
    assert report.mode == "hash-paged"
    assert not report.walk_clipped
    assert leng.merkle_root() == reng.merkle_root()


def test_walk_escalates_forced_refresh_on_deep_donor_lag(
    two_nodes, monkeypatch
):
    """A donor whose probe stamp admits a lag past the limit gets exactly
    ONE forced-refresh re-probe before the walk descends."""
    (leng, lsrv), (reng, rsrv) = two_nodes
    items = {f"fr{i:04d}": f"v{i}" for i in range(300)}
    _fill(reng, items)
    local = dict(items)
    local["fr0042"] = "stale"
    _fill(leng, local)

    forced = {"n": 0, "probes": 0}
    real = MerkleKVClient.tree_level

    def lagging_probe(self, level, lo, hi, force=False):
        rows, n = real(self, level, lo, hi, force=force)
        if force:
            forced["n"] += 1
        elif (level, lo, hi) == (0, 0, 0):
            forced["probes"] += 1
            if forced["probes"] == 1:
                # First probe: the donor admits a deep pump lag.
                self.last_stamp = (5, 10_000_000)
        return rows, n

    monkeypatch.setattr(MerkleKVClient, "tree_level", lagging_probe)
    mgr = SyncManager(
        leng, device="cpu", mode="bisect", retry=FAST, tree_lag_limit=100
    )
    report = mgr.sync_once("127.0.0.1", rsrv.port)
    assert report.forced_refreshes == 1
    assert forced["n"] == 1
    assert report.mode == "bisect"
    assert leng.merkle_root() == reng.merkle_root()


def test_antientropy_converges_under_write_storm_with_trailing_donor(
    broker,
):
    """Acceptance regression: an active write storm against a
    bounded-trailing donor (pump-published tree, stamped answers) never
    wedges anti-entropy — repeated cycles during the storm stay sane, and
    the first post-storm cycle converges both engines bit-identically."""
    topic = f"storm-{uuid.uuid4().hex[:8]}"
    donor = _Node(broker, topic + "-d", "sd", max_staleness_ms=50.0)
    walker_eng = NativeEngine("mem")
    try:
        for i in range(256):
            donor.client.set(f"ws{i:04d}", f"v{i}")
        _wait_ready(donor)
        for i in range(0, 256, 7):
            walker_eng.set(f"ws{i:04d}".encode(), b"diverged")
        mgr = SyncManager(
            walker_eng, device="cpu", mode="bisect", retry=FAST
        )
        stop = threading.Event()

        def storm():
            i = 0
            while not stop.is_set():
                donor.client.set(f"ws{i % 256:04d}", f"storm{i}")
                i += 1

        t = threading.Thread(target=storm, daemon=True)
        t.start()
        try:
            for _ in range(3):
                try:
                    mgr.sync_once("127.0.0.1", donor.server.port)
                except Exception:
                    pass  # a mid-storm cycle may checkpoint; next resumes
        finally:
            stop.set()
            t.join(timeout=10)
        # Post-storm: cycles until bit-identical (bounded window closes,
        # the donor's tree catches up, the walk finishes the repair).
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                rep = mgr.sync_once("127.0.0.1", donor.server.port)
            except Exception:
                continue
            if walker_eng.merkle_root() == donor.engine.merkle_root():
                break
        assert walker_eng.merkle_root() == donor.engine.merkle_root()
        assert rep is not None
    finally:
        donor.close()
        walker_eng.close()


# ----------------------------------------------------------- config


def test_device_config_parses_and_validates():
    cfg = Config.from_dict(
        {"device": {"max_staleness_ms": 50, "max_staleness_versions": 1024}}
    )
    assert cfg.device.max_staleness_ms == 50.0
    assert cfg.device.max_staleness_versions == 1024
    with pytest.raises(ValueError):
        Config.from_dict({"device": {"max_staleness_ms": 0}})
    with pytest.raises(ValueError):
        Config.from_dict({"device": {"max_staleness_versions": -1}})


def test_async_client_stamp_parity(bare):
    """Async client parses stamped headers and falls back identically."""
    import asyncio

    from merklekv_tpu.client import AsyncMerkleKVClient

    eng, srv = bare
    for i in range(4):
        eng.set(f"ak{i}".encode(), b"v")

    async def go():
        c = AsyncMerkleKVClient("127.0.0.1", srv.port, timeout=10.0)
        c.version_stamps = True
        await c.connect()
        try:
            rows, n = await c.tree_level(0, 0, 0)
            assert n == 4 and c._peer_stamped is True
            ver, lag = c.last_stamp
            assert ver == eng.version() and lag == 0
            await c.leaf_hashes_page(2)
            assert c.last_stamp == (eng.version(), 0)
            root = await c.hash()
            assert root == eng.merkle_root().hex()
            assert c.last_stamp == (eng.version(), 0)
            _, n = await c.tree_level(0, 0, 0, force=True)
            assert n == 4
        finally:
            await c.close()

    asyncio.run(go())

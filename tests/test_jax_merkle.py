"""Golden parity: JAX/TPU Merkle engine vs the CPU reference core."""

import numpy as np
import pytest

from merklekv_tpu.merkle.cpu import MerkleTree
from merklekv_tpu.merkle.jax_engine import (
    JaxMerkleTree,
    build_levels_jit,
    leaf_digests,
    tree_root,
    tree_root_capacity,
)
from merklekv_tpu.ops.sha256 import digest_to_bytes, digests_to_bytes


def _items(n, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        k = f"key:{rng.integers(0, 10**9):09d}:{i}"
        v = "v" * int(rng.integers(0, 40)) + str(i)
        out.append((k, v))
    return out


@pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 7, 8, 9, 15, 16, 17, 33, 100])
def test_root_parity_with_cpu(n):
    items = _items(n, seed=n)
    cpu = MerkleTree.from_items(items)
    dev = JaxMerkleTree()
    for k, v in items:
        dev.insert(k, v)
    assert dev.root_hex() == cpu.root_hex()


def test_all_levels_parity():
    items = _items(13, seed=42)
    cpu = MerkleTree.from_items(items)
    ordered = sorted((k.encode(), v.encode()) for k, v in items)
    leaves = leaf_digests([k for k, _ in ordered], [v for _, v in ordered])
    dev_levels = build_levels_jit(leaves)
    cpu_levels = cpu.levels
    assert len(dev_levels) == len(cpu_levels)
    for dl, cl in zip(dev_levels, cpu_levels):
        assert digests_to_bytes(np.asarray(dl)) == cl


def test_unicode_and_nul_parity():
    items = [("", ""), ("\x00", "\x00v"), ("héllo", "wörld"), ("世界", "值")]
    cpu = MerkleTree.from_items(items)
    dev = JaxMerkleTree()
    for k, v in items:
        dev.insert(k, v)
    assert dev.root_hex() == cpu.root_hex()


def test_mutation_and_removal():
    dev = JaxMerkleTree()
    cpu = MerkleTree()
    for k, v in _items(20, seed=5):
        dev.insert(k, v)
        cpu.insert(k, v)
    ks = sorted(dict(_items(20, seed=5)))
    for k in ks[::3]:
        dev.remove(k)
        cpu.remove(k)
    assert dev.root_hex() == cpu.root_hex()
    dev.clear()
    assert dev.root_hex() == "0" * 64
    assert len(dev) == 0


@pytest.mark.parametrize("n", [1, 2, 3, 5, 8, 11, 16, 29, 32])
def test_capacity_build_matches_static(n):
    items = _items(n, seed=100 + n)
    ordered = sorted((k.encode(), v.encode()) for k, v in items)
    leaves = np.asarray(
        leaf_digests([k for k, _ in ordered], [v for _, v in ordered])
    )
    cap = 32
    padded = np.zeros((cap, 8), np.uint32)
    padded[:n] = leaves
    got = digest_to_bytes(np.asarray(tree_root_capacity(padded, np.int32(n))))
    want = digest_to_bytes(np.asarray(tree_root(leaves)))
    assert got == want


def test_capacity_rejects_non_power_of_two():
    with pytest.raises(ValueError):
        tree_root_capacity(np.zeros((12, 8), np.uint32), np.int32(3))


def test_insertion_order_independence():
    items = _items(17, seed=9)
    a, b = JaxMerkleTree(), JaxMerkleTree()
    for k, v in items:
        a.insert(k, v)
    for k, v in reversed(items):
        b.insert(k, v)
    assert a.root_hex() == b.root_hex()

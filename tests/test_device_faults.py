"""Device-plane fault containment (ISSUE 13): unit layer.

The dispatch guard (deadline / classify / retry-once / abandonment), the
shared environment|code classifier, the chaos injector, the degradation
ladder's policy, the CPU golden rung's bit-identity, the guarded diff
fallback, bench_gate's structured-weather skip, and the blackbox anomaly
surfacing. The mirror-level chaos (per-rung transitions on the 8-way host
mesh, pump-alive under hang, scrub, heal) lives in test_device_ladder.py.
"""

import threading
import time

import pytest

from merklekv_tpu.cluster.retry import RetryPolicy
from merklekv_tpu.device.guard import (
    DeviceDispatchError,
    DispatchGuard,
    DispatchHungError,
)
from merklekv_tpu.device.ladder import DeviceBackendLadder, rung_sequence
from merklekv_tpu.testing.device_faults import DeviceFaultInjector
from merklekv_tpu.utils.errorkind import (
    CODE,
    ENVIRONMENT,
    classify_error,
    classify_exception,
)

FAST = RetryPolicy(first_delay=0.01, max_delay=0.02, jitter=0.0, attempts=2)


# ------------------------------------------------------------ classifier

@pytest.mark.parametrize("msg", [
    "RuntimeError: need 8 devices, have 1",
    "unable to initialize backend 'tpu'",
    "DEADLINE_EXCEEDED: rpc timed out",
    "watchdog: 240s deadline expired in phase 'mesh-init'",
    "device dispatch 'shard8_build' failed: dispatch deadline 500ms "
    "expired",
    "connection reset by peer",
])
def test_classifier_environment_patterns(msg):
    assert classify_error(msg) == ENVIRONMENT


@pytest.mark.parametrize("msg", [
    "AssertionError: sharded root != single-device root",
    "ValueError: shapes (8, 8) and (4, 8) are incompatible",
    "KeyError: b'missing'",
])
def test_classifier_code_default(msg):
    assert classify_error(msg) == CODE


def test_classifier_exception_types_are_environment():
    # OSError-family failures are environment even with pattern-less
    # messages (errno text varies by libc).
    assert classify_exception(OSError("whatever")) == ENVIRONMENT
    assert classify_exception(TimeoutError()) == ENVIRONMENT
    assert classify_exception(ValueError("bad shape")) == CODE


def test_classifier_is_the_probes_classifier():
    """__graft_entry__ must classify through the shared module (the
    dedup satellite: one pattern table, three consumers)."""
    import __graft_entry__ as ge

    assert ge._classify_error is classify_error


# ------------------------------------------------------------ guard

def test_guard_passthrough_and_deadline_abandonment():
    g = DispatchGuard(deadline_ms=300, policy=FAST)
    assert g.run("t", lambda: 41 + 1) == 42
    t0 = time.monotonic()
    with pytest.raises(DispatchHungError) as ei:
        g.run("t", lambda: time.sleep(3))
    assert time.monotonic() - t0 < 2.0, "guard waited past its deadline"
    assert ei.value.kind == ENVIRONMENT
    # The wedged worker was abandoned; a fresh one serves the next call.
    assert g.run("t", lambda: 7) == 7


def test_guard_retries_environment_once_then_raises_typed():
    g = DispatchGuard(deadline_ms=0, policy=FAST)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("unable to initialize backend (blip)")
        return "ok"

    assert g.run("t", flaky) == "ok"
    assert calls["n"] == 2  # one transparent retry

    calls["n"] = 0

    def dead():
        calls["n"] += 1
        raise RuntimeError("unable to initialize backend (persistent)")

    with pytest.raises(DeviceDispatchError) as ei:
        g.run("t", dead)
    assert calls["n"] == 2  # retried once, then typed raise
    assert ei.value.kind == ENVIRONMENT
    assert ei.value.label == "t"


def test_guard_code_errors_never_retry():
    g = DispatchGuard(deadline_ms=0, policy=FAST)
    calls = {"n": 0}

    def buggy():
        calls["n"] += 1
        raise ValueError("scatter index shape mismatch")

    with pytest.raises(DeviceDispatchError) as ei:
        g.run("t", buggy)
    assert calls["n"] == 1
    assert ei.value.kind == CODE


def test_guard_nested_call_runs_inline_no_false_hang():
    """A guarded call issued FROM the guard worker (query-path gather
    triggering a staged flush) must run inline, not deadlock into a
    false hang against the busy single worker."""
    g = DispatchGuard(deadline_ms=500, policy=FAST)
    assert g.run("outer", lambda: g.run("inner", lambda: 5)) == 5


# ------------------------------------------------------------ injector

def test_injector_fail_nth_and_count():
    inj = DeviceFaultInjector(match="scatter", at=2, count=1)
    g = DispatchGuard(deadline_ms=0, policy=FAST)
    inj.install()
    try:
        assert g.run("scatter", lambda: 1) == 1   # matched #1: below at
        # matched #2 fails, matched #3 (the guard's retry) passes — the
        # injected blip is absorbed by the retry budget.
        assert g.run("scatter", lambda: 2) == 2
        assert inj.failures == 1
        assert g.run("build", lambda: 3) == 3     # label not matched
    finally:
        inj.uninstall()


def test_injector_persistent_until_heal():
    inj = DeviceFaultInjector(match="shard*", mode="fail")
    g = DispatchGuard(deadline_ms=0, policy=FAST)
    with inj:
        with pytest.raises(DeviceDispatchError):
            g.run("shard8_build", lambda: 1)
        with pytest.raises(DeviceDispatchError):
            g.run("shard2_scatter", lambda: 1)
        assert g.run("build", lambda: 1) == 1  # single-device unscathed
        inj.heal()
        assert g.run("shard8_build", lambda: 1) == 1
        inj.unheal()
        with pytest.raises(DeviceDispatchError):
            g.run("shard8_build", lambda: 1)


def test_injector_hang_exercises_abandonment():
    inj = DeviceFaultInjector(match="*", mode="hang", hang_s=2.0)
    g = DispatchGuard(deadline_ms=200, policy=FAST)
    with inj:
        t0 = time.monotonic()
        with pytest.raises(DispatchHungError):
            g.run("build", lambda: 1)
        assert time.monotonic() - t0 < 1.5
        assert inj.hangs == 1
    assert g.run("build", lambda: 1) == 1  # uninstalled + fresh worker


def test_injector_env_spec_roundtrip():
    inj = DeviceFaultInjector.from_spec("fail:shard*:3")
    assert inj._match == "shard*" and inj._mode == "fail" and inj._at == 3
    with pytest.raises(ValueError):
        DeviceFaultInjector.from_spec("fail")
    with pytest.raises(ValueError):
        DeviceFaultInjector(mode="explode")


# ------------------------------------------------------------ ladder policy

def test_rung_sequence_shapes():
    assert rung_sequence(8) == [8, 4, 2, 1, 0]
    assert rung_sequence(2) == [2, 1, 0]
    assert rung_sequence(1) == [1, 0]
    assert rung_sequence(0) == [1, 0]


def test_ladder_degrade_threshold_and_immediate():
    lad = DeviceBackendLadder(8, degrade_after=2, heal_policy=FAST)
    assert lad.current() == 8 and not lad.degraded()
    assert not lad.note_failure(ENVIRONMENT, "drain")
    assert lad.note_failure(ENVIRONMENT, "drain")   # second one steps
    assert lad.current() == 4 and lad.degraded()
    # Success resets the consecutive counter.
    assert not lad.note_failure(ENVIRONMENT, "drain")
    lad.note_success()
    assert not lad.note_failure(ENVIRONMENT, "drain")
    # Build failures step immediately.
    assert lad.note_failure(ENVIRONMENT, "build", immediate=True)
    assert lad.current() == 2
    # Walk to the bottom: the CPU rung never steps further.
    assert lad.note_failure(ENVIRONMENT, "build", immediate=True)
    assert lad.note_failure(ENVIRONMENT, "build", immediate=True)
    assert lad.current() == 0 and lad.at_bottom()
    assert not lad.note_failure(ENVIRONMENT, "build", immediate=True)
    assert lad.current() == 0


def test_ladder_heal_probe_targets_top_first_then_walks_down():
    lad = DeviceBackendLadder(8, degrade_after=1, heal_policy=FAST)
    for _ in range(3):  # 8 -> 4 -> 2 -> 1
        lad.note_failure(ENVIRONMENT, "drain")
    assert lad.current() == 1
    time.sleep(0.03)
    assert lad.heal_due()
    assert lad.probe_target() == 8          # top first: common full heal
    assert lad.note_probe(False) is None
    time.sleep(0.03)
    assert lad.probe_target() == 4          # walks down after a miss
    assert lad.note_probe(False) is None
    time.sleep(0.03)
    assert lad.probe_target() == 2
    assert lad.note_probe(True) == 2        # partial heal climbs there
    assert lad.current() == 2 and lad.degraded()
    time.sleep(0.03)
    assert lad.probe_target() == 8          # keeps probing upward
    assert lad.note_probe(True) == 8
    assert not lad.degraded()


def test_ladder_records_flight_events_and_counters():
    from merklekv_tpu.obs.flightrec import get_recorder

    lad = DeviceBackendLadder(2, degrade_after=1, heal_policy=FAST)
    lad.note_failure(ENVIRONMENT, "drain")
    time.sleep(0.03)
    assert lad.note_probe(True) == 2
    kinds = [e.kind for e in get_recorder().last(10)]
    assert "device_degraded" in kinds and "device_healed" in kinds
    deg = [e for e in get_recorder().last(10)
           if e.kind == "device_degraded"][-1]
    assert deg.fields["from_rung"] == 2 and deg.fields["to_rung"] == 1
    assert deg.fields["kind"] == ENVIRONMENT


# ------------------------------------------------------------ CPU rung

def test_cpu_state_bit_identical_to_golden_tree():
    from merklekv_tpu.merkle.cpu import build_levels
    from merklekv_tpu.merkle.cpu_state import CpuMerkleState
    from merklekv_tpu.merkle.encoding import leaf_hash

    items = {b"cpu:%04d" % i: b"v%d" % i for i in range(111)}
    st = CpuMerkleState.from_items(items.items())

    def golden():
        return build_levels(
            [leaf_hash(k, v) for k, v in sorted(items.items())]
        )

    assert st.root_hex() == golden()[-1][0].hex()
    # Staging contract: pending stays invisible until flush.
    st.apply([(b"cpu:0000", b"changed")])
    assert st.pending_count() == 1
    assert st.root_hex(flush=False) == golden()[-1][0].hex()
    items[b"cpu:0000"] = b"changed"
    st.flush_pending()
    assert st.pending_count() == 0
    assert st.root_hex(flush=False) == golden()[-1][0].hex()
    # Structural change + every-level TREELEVEL parity.
    st.apply([(b"zzz:new", b"n"), (b"cpu:0001", None)])
    items[b"zzz:new"] = b"n"
    del items[b"cpu:0001"]
    st.flush_pending()
    glv = golden()
    for lvl in range(len(glv)):
        rows, n = st.level_nodes(lvl, 0, len(glv[lvl]))
        assert n == len(items)
        assert [d for _, d in rows] == glv[lvl]
    assert st._n_shards == 0  # the backend_level code for the CPU rung


# ------------------------------------------------------------ diff fallback

def test_divergence_engine_falls_back_bit_identical_under_fault():
    import numpy as np

    from merklekv_tpu.merkle.diff import (
        divergence_masks_engine,
        divergence_masks_np,
    )

    rng = np.random.RandomState(3)
    n, r = 64, 4
    digests = np.tile(
        rng.randint(0, 2**32, size=(1, n, 8), dtype=np.uint64).astype(
            np.uint32
        ),
        (r, 1, 1),
    )
    digests[2, 5] ^= 1
    present = np.ones((r, n), bool)
    present[3, 0] = False
    golden = divergence_masks_np(digests, present)
    with DeviceFaultInjector(match="shard*_diff", mode="fail"):
        masks = divergence_masks_engine(digests, present, min_keys=0)
    assert np.array_equal(np.asarray(masks), golden)


# ------------------------------------------------------------ bench_gate

def test_bench_gate_skips_environment_weather_rounds():
    import sys
    sys.path.insert(0, "tools")
    from bench_gate import extract_scenarios, round_weather

    weather = {
        "rc": 0,
        "parsed": {
            "metric": "merkle_rebuild_diff_keys_per_s",
            "value": None,
            "unit": "keys/s",
            "error": "RuntimeError: unable to initialize backend",
            "error_kind": "environment",
        },
        "tail": "",
    }
    assert extract_scenarios(weather) == {}  # never a baseline
    assert round_weather(weather) == "environment"
    # A code-kind crash is also skipped but not called weather.
    broken = {
        "rc": 1,
        "parsed": {
            "metric": "m", "value": None, "unit": "",
            "error": "AssertionError: boom", "error_kind": "code",
        },
    }
    assert round_weather(broken) == "code"
    # Legacy rounds without the field keep the old anonymous skip.
    assert round_weather({"rc": 1, "parsed": None}) is None


def test_bench_gate_direction_for_fault_recovery_metrics():
    sysmod = __import__("sys")
    sysmod.path.insert(0, "tools")
    from bench_gate import lower_is_better

    assert not lower_is_better("device_fault_queries_per_s", "queries/s")
    assert lower_is_better("device_fault_reclimb_ms", "ms")


# ------------------------------------------------------------ blackbox

def test_blackbox_surfaces_device_ladder_events_as_anomalies():
    from merklekv_tpu.obs.blackbox import SpillDoc, find_anomalies, merge_timeline
    from merklekv_tpu.obs.flightrec import FlightEvent

    def evt(evt_kind, seq, **fields):
        # The wire gotcha all over again: an event's own `kind` field
        # (the classifier verdict) must not collide with the FlightEvent
        # kind — keyword-splatting both through one signature does.
        return FlightEvent(
            seq=seq, wall_ns=1_000_000_000 + seq, mono_ns=seq,
            kind=evt_kind,
            fields={k: str(v) for k, v in fields.items()},
        )

    doc = SpillDoc(
        path="x/flight", meta={"node": "n1"},
        events=[
            evt("device_degraded", 1, from_rung=8, to_rung=4,
                kind="environment", where="drain"),
            evt("device_fallback", 2, rung=4),
            evt("device_corruption", 3, leaf_index=17, rung=4),
            evt("device_healed", 4, from_rung=4, to_rung=8),
        ],
    )
    timeline = merge_timeline([doc])
    anomalies = find_anomalies([doc], timeline)
    kinds = {a.kind for a in anomalies}
    assert "device_degraded" in kinds
    assert "device_fallback" in kinds
    assert "device_corruption" in kinds
    deg = [a for a in anomalies if a.kind == "device_degraded"][0]
    assert "environment" in deg.detail and "8 -> 4" in deg.detail


# ------------------------------------------------------------ guard metrics

def test_guard_counts_timeouts_and_retries():
    from merklekv_tpu.obs.metrics import get_metrics

    def counter(name):
        return get_metrics().snapshot()["counters"].get(name, 0)

    base_t = counter("device.guard_timeouts")
    base_r = counter("device.guard_retries")
    g = DispatchGuard(deadline_ms=150, policy=FAST)
    with pytest.raises(DispatchHungError):
        g.run("t", lambda: time.sleep(1.5))
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("connection reset by peer")
        return 1

    assert g.run("t", flaky) == 1
    assert counter("device.guard_timeouts") == base_t + 1
    assert counter("device.guard_retries") == base_r + 1

"""Chaos suite: anti-entropy convergence under injected partial failure.

Every scenario drives a real 2-node (or 3-node) sync through the
FaultInjector TCP proxy (merklekv_tpu/testing/faults.py) with a FIXED seed,
so a failure replays bit-identically. The acceptance bar (ISSUE 1): the
nodes converge to identical Merkle roots under chunk drop, delay+reorder,
duplication, truncation, and a peer killed mid-sync — and a mid-sync death
leaves partial repairs applied, checkpoints the remainder, and the next
cycle RESUMES instead of restarting.

The long randomized soak is marked ``slow`` (excluded from tier-1); the
fixed-seed cases here are the tier-1 smoke coverage.
"""

from __future__ import annotations

import time

import pytest

from merklekv_tpu.cluster.retry import Deadline, RetryPolicy
from merklekv_tpu.cluster.sync import SyncManager
from merklekv_tpu.native_bindings import NativeEngine, NativeServer
from merklekv_tpu.testing.faults import FaultInjector, FaultyTransport

# Fast-failing policy for chaos runs: short op timeout so injected stalls
# cost milliseconds, a couple of connect retries, bounded cycle budget.
FAST = RetryPolicy(
    first_delay=0.01,
    max_delay=0.05,
    jitter=0.0,
    attempts=2,
    op_timeout=0.5,
    op_deadline=30.0,
)


def fill(eng, items):
    for k, v in items.items():
        eng.set(k.encode(), v.encode())


def snapshot(eng) -> dict:
    return dict(eng.snapshot())


class ChaosPair:
    """Local engine + remote engine/server, injector in front of remote."""

    def __init__(
        self,
        seed: int,
        divergent: int = 120,
        mget_batch: int = 16,
        hash_page: int = 64,
    ):
        self.local = NativeEngine("mem")
        self.remote = NativeEngine("mem")
        self.srv = NativeServer(self.remote, "127.0.0.1", 0)
        self.srv.start()
        self.inj = FaultInjector("127.0.0.1", self.srv.port, seed=seed)
        self.degraded: list[tuple[str, str]] = []
        self.mgr = SyncManager(
            self.local,
            device="cpu",
            mget_batch=mget_batch,
            retry=FAST,
            hash_page=hash_page,
            on_peer_degraded=lambda p, r: self.degraded.append((p, r)),
        )
        # Local first, remote second: remote writes are newer, so resumed
        # (LWW-conditional) repairs deterministically win.
        fill(self.local, {f"k{i:04d}": "stale" for i in range(divergent // 2)})
        fill(self.remote, {f"k{i:04d}": f"fresh-{i}" for i in range(divergent)})

    @property
    def peer(self) -> str:
        return f"{self.inj.host}:{self.inj.port}"

    def sync_until_converged(self, max_cycles: int = 60) -> int:
        """Run sync cycles through the injector until roots match; returns
        the number of cycles used. Individual cycles are ALLOWED to die —
        that is the point — but the sequence must converge."""
        for cycle in range(1, max_cycles + 1):
            try:
                self.mgr.sync_once(self.inj.host, self.inj.port)
            except Exception:
                pass
            if self.local.merkle_root() == self.remote.merkle_root():
                return cycle
        raise AssertionError(
            f"no convergence in {max_cycles} cycles "
            f"(dropped={self.inj.chunks_dropped} "
            f"dup={self.inj.chunks_duplicated} "
            f"reordered={self.inj.chunks_reordered})"
        )

    def close(self):
        self.mgr.stop()
        self.inj.close()
        self.srv.close()
        self.local.close()
        self.remote.close()


@pytest.fixture
def make_pair():
    pairs = []

    def _make(seed: int, **kw) -> ChaosPair:
        p = ChaosPair(seed, **kw)
        pairs.append(p)
        return p

    yield _make
    for p in pairs:
        p.close()


# --------------------------------------------------------------- fault mix


def test_converges_under_drop(make_pair):
    """30% chunk drop in both directions: cycles die mid-stream, partial
    repairs stick, checkpoints resume — and the pair still converges."""
    p = make_pair(seed=7)
    p.inj.set_faults("both", drop_rate=0.3)
    cycles = p.sync_until_converged()
    assert snapshot(p.local) == snapshot(p.remote)
    assert p.inj.chunks_dropped > 0, "fault never fired; scenario is vacuous"
    # The whole point of resumable sessions: progress survives the faults.
    assert cycles >= 1


def test_converges_under_delay_and_reorder(make_pair):
    p = make_pair(seed=11)
    p.inj.set_faults("both", delay=(0.0, 0.02), reorder_rate=0.3)
    p.sync_until_converged()
    assert snapshot(p.local) == snapshot(p.remote)
    assert p.inj.chunks_reordered > 0, "fault never fired"


def test_converges_under_duplication(make_pair):
    p = make_pair(seed=13)
    p.inj.set_faults("both", dup_rate=0.4)
    p.sync_until_converged()
    assert snapshot(p.local) == snapshot(p.remote)
    assert p.inj.chunks_duplicated > 0, "fault never fired"


def test_converges_under_truncation(make_pair):
    p = make_pair(seed=17)
    p.inj.set_faults("s2c", truncate_rate=0.2)
    p.sync_until_converged()
    assert snapshot(p.local) == snapshot(p.remote)
    assert p.inj.chunks_truncated > 0, "fault never fired"


# ------------------------------------------------- peer death + resumption


def test_peer_death_mid_sync_checkpoints_and_resumes(make_pair):
    """Kill the peer after the 20th applied repair: the applied prefix
    stays, the remainder is checkpointed, the peer is marked degraded,
    and the next cycle RESUMES (fetches only the remainder) rather than
    restarting from scratch."""
    p = make_pair(seed=23, divergent=120, mget_batch=8)
    repairs: list[bytes] = []

    def killer_listener(key, value, ts=None):
        repairs.append(key)
        if len(repairs) == 20:
            p.inj.kill_peer()

    p.mgr._repair_listener = killer_listener

    with pytest.raises(Exception):
        p.mgr.sync_once(p.inj.host, p.inj.port)

    # Partial repairs stayed applied.
    local_now, remote_now = snapshot(p.local), snapshot(p.remote)
    applied = sum(1 for k, v in remote_now.items() if local_now.get(k) == v)
    assert 20 <= applied < len(remote_now), (applied, len(remote_now))
    # The remainder is checkpointed and the peer marked degraded.
    sess = p.mgr.session_for(p.peer)
    assert sess is not None and len(sess.pending_sets) > 0
    assert any(peer == p.peer for peer, _ in p.degraded)

    # Peer restarts; the next cycle resumes from the checkpoint.
    p.mgr._repair_listener = None
    p.inj.revive()
    report = p.mgr.sync_once(p.inj.host, p.inj.port)
    assert report.resumed is True
    assert any("resuming session" in d for d in report.details)
    # Resume drained the checkpointed remainder and continued the paged
    # walk from the cursor — the already-repaired prefix was NOT refetched.
    assert report.values_fetched >= len(sess.pending_sets)
    assert report.values_fetched <= 120 - applied
    assert p.local.merkle_root() == p.remote.merkle_root()
    assert p.mgr.session_for(p.peer) is None


def test_session_abandoned_after_max_attempts(make_pair):
    """A session that keeps failing is dropped (fresh diff next cycle),
    never resumed forever."""
    from merklekv_tpu.cluster import sync as sync_mod

    p = make_pair(seed=29)
    sess = sync_mod.SyncSession(
        peer=p.peer,
        pending_sets=[(b"k0000", 1)],
        attempts=sync_mod._SESSION_MAX_ATTEMPTS,
    )
    p.mgr._sessions[p.peer] = sess
    report = p.mgr.sync_once(p.inj.host, p.inj.port)
    assert report.resumed is False  # stale session discarded, normal cycle
    assert p.local.merkle_root() == p.remote.merkle_root()


def test_multi_peer_cycle_survives_mid_sync_peer_death(make_pair):
    """sync_multi: one peer dying mid-repair no longer aborts the cycle —
    the other peer's repairs land, the dead peer is checkpointed and
    degraded, and the next cycle resumes it."""
    local = NativeEngine("mem")
    eng_a, eng_b = NativeEngine("mem"), NativeEngine("mem")
    srv_a = NativeServer(eng_a, "127.0.0.1", 0)
    srv_b = NativeServer(eng_b, "127.0.0.1", 0)
    srv_a.start()
    srv_b.start()
    inj_b = FaultInjector("127.0.0.1", srv_b.port, seed=31)
    degraded: list[str] = []
    killed = []

    def listener(key, value, ts=None):
        # First b-key repair kills peer B mid-stream.
        if key.startswith(b"b") and not killed:
            killed.append(key)
            inj_b.kill_peer()

    mgr = SyncManager(
        local,
        device="cpu",
        mget_batch=8,
        retry=FAST,
        repair_listener=listener,
        on_peer_degraded=lambda peer, r: degraded.append(peer),
    )
    try:
        fill(eng_a, {f"a{i:03d}": f"va{i}" for i in range(24)})
        fill(eng_b, {f"b{i:03d}": f"vb{i}" for i in range(32)})
        peer_a = f"127.0.0.1:{srv_a.port}"
        peer_b = f"{inj_b.host}:{inj_b.port}"

        report = mgr.sync_multi([peer_a, peer_b])
        # Peer A's repairs all landed despite B dying mid-cycle.
        local_snap = snapshot(local)
        assert all(
            local_snap.get(k) == v for k, v in snapshot(eng_a).items()
        ), "live peer's repairs must not be lost to the dead peer"
        assert peer_b in report.degraded
        assert peer_b in degraded
        sess = mgr.session_for(peer_b)
        assert sess is not None and len(sess.pending_sets) > 0

        # B restarts: next cycle resumes its checkpoint and converges.
        mgr._repair_listener = None
        inj_b.revive()
        report2 = mgr.sync_multi([peer_a, peer_b])
        assert peer_b in report2.resumed_peers
        local_snap = snapshot(local)
        for k, v in snapshot(eng_b).items():
            assert local_snap.get(k) == v
    finally:
        mgr.stop()
        inj_b.close()
        srv_a.close()
        srv_b.close()
        local.close()
        eng_a.close()
        eng_b.close()


# ---------------------------------------------------- deadline checkpoints


def test_expired_deadline_checkpoints_without_error(make_pair):
    """An exhausted per-peer cycle budget checkpoints the remainder and
    returns cleanly; the next cycle resumes."""
    p = make_pair(seed=37, divergent=80, mget_batch=8)
    # A deadline that expires immediately: every batch checkpoints.
    expired = Deadline(0.0)
    time.sleep(0.001)
    from merklekv_tpu.client import MerkleKVClient
    from merklekv_tpu.cluster.sync import SyncReport

    report = SyncReport(peer=p.peer)

    with MerkleKVClient(p.inj.host, p.inj.port, timeout=1.0) as c:
        pairs = [(f"k{i:04d}".encode(), 1) for i in range(40)]
        p.mgr._repair_sets_resumable(c, p.peer, pairs, report, expired, lww=True)
    sess = p.mgr.session_for(p.peer)
    assert sess is not None and len(sess.pending_sets) == 40
    assert any("deadline expired" in d for d in report.details)
    # Next normal cycle (fresh deadline) drains the session and converges.
    rep = p.mgr.sync_once(p.inj.host, p.inj.port)
    assert rep.resumed is True
    assert p.local.merkle_root() == p.remote.merkle_root()


# -------------------------------------------------- device-path degradation


def test_device_failure_falls_back_to_cpu(make_pair, monkeypatch):
    """A TPU/Pallas init failure degrades to host hashing with a one-time
    warning instead of killing every cycle."""
    import warnings

    from merklekv_tpu.cluster import sync as sync_mod
    from merklekv_tpu.utils import jaxenv

    # Isolate the sticky global so this test cannot leak into others.
    monkeypatch.setattr(jaxenv, "_device_fallback", False)

    def boom(items):
        raise RuntimeError("Unable to initialize backend 'tpu'")

    monkeypatch.setattr(sync_mod, "_leaf_map_device", boom)
    p = make_pair(seed=41, divergent=40)
    p.mgr._device = "tpu"  # force the device path
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        p.mgr.sync_once(p.inj.host, p.inj.port)
        p.mgr.sync_once(p.inj.host, p.inj.port)
    assert p.local.merkle_root() == p.remote.merkle_root()
    assert jaxenv.device_failed()
    relevant = [w for w in caught if "falling back" in str(w.message)]
    assert len(relevant) == 1, "device-failure warning must fire exactly once"


# -------------------------------------------------- message-level transport


def test_faulty_transport_deterministic_faults():
    """FaultyTransport: whole-message drop/dup/reorder under a fixed seed,
    replayed identically."""

    class Recorder:
        def __init__(self):
            self.messages = []

        def publish(self, topic, payload):
            self.messages.append(payload)

        def subscribe(self, *a):
            pass

        def unsubscribe(self, *a):
            pass

        def close(self):
            pass

    def run(seed):
        rec = Recorder()
        ft = FaultyTransport(
            rec, seed=seed, drop_rate=0.2, dup_rate=0.2, reorder_rate=0.2
        )
        for i in range(50):
            ft.publish("t", b"m%d" % i)
        ft.flush_held()
        return rec.messages, (ft.dropped, ft.duplicated, ft.reordered)

    msgs1, stats1 = run(99)
    msgs2, stats2 = run(99)
    assert msgs1 == msgs2, "same seed must replay the same schedule"
    assert stats1 == stats2
    dropped, duplicated, reordered = stats1
    assert dropped > 0 and duplicated > 0 and reordered > 0
    # Every non-dropped message is delivered (dups add, drops remove).
    assert len(msgs1) == 50 - dropped + duplicated


def test_replication_converges_through_faulty_transport():
    """Replication events through a lossy/reordering/duplicating fabric:
    op-id dedupe + LWW absorb the faults, anti-entropy repairs the drops,
    and the nodes converge."""
    from merklekv_tpu.cluster.replicator import Replicator
    from merklekv_tpu.cluster.transport import InProcessBus

    bus = InProcessBus()
    engines, servers, reps = [], [], []
    try:
        for i in range(2):
            eng = NativeEngine("mem")
            srv = NativeServer(eng, "127.0.0.1", 0)
            srv.start()
            ft = FaultyTransport(
                bus, seed=50 + i, drop_rate=0.3, dup_rate=0.3,
                reorder_rate=0.2,
            )
            rep = Replicator(
                eng, srv, ft, topic_prefix="chaos", node_id=f"n{i}"
            )
            rep.start()
            engines.append(eng)
            servers.append(srv)
            reps.append(rep)

        from merklekv_tpu.client import MerkleKVClient

        with MerkleKVClient("127.0.0.1", servers[0].port) as c0, \
                MerkleKVClient("127.0.0.1", servers[1].port) as c1:
            for i in range(40):
                (c0 if i % 2 == 0 else c1).set(f"fx{i:03d}", f"v{i}")
        for rep in reps:
            rep.flush()
        time.sleep(0.3)  # let the bus dispatcher drain

        # Anti-entropy backstop repairs whatever the faults ate.
        mgr = SyncManager(engines[0], device="cpu", retry=FAST)
        for _ in range(5):
            try:
                mgr.sync_once("127.0.0.1", servers[1].port)
            except Exception:
                pass
            if engines[0].merkle_root() == engines[1].merkle_root():
                break
        # One-way sync converges node0 to node1; finish with reverse pass.
        mgr1 = SyncManager(engines[1], device="cpu", retry=FAST)
        mgr1.sync_once("127.0.0.1", servers[0].port)
        assert engines[0].merkle_root() == engines[1].merkle_root()
        assert snapshot(engines[0]) == snapshot(engines[1])
    finally:
        for rep in reps:
            rep.stop()
        for srv in servers:
            srv.close()
        for eng in engines:
            eng.close()
        bus.close()


# ------------------------------------------------------------ slow soak


@pytest.mark.slow
@pytest.mark.parametrize("seed", [101, 202, 303, 404, 505])
def test_soak_full_fault_mix(make_pair, seed):
    """Randomized (but seeded) soak: every fault class at once, larger
    keyspace, must still converge. Excluded from tier-1 via ``slow``."""
    p = make_pair(seed=seed, divergent=400, mget_batch=32)
    p.inj.set_faults(
        "both",
        drop_rate=0.15,
        dup_rate=0.15,
        reorder_rate=0.15,
        delay=(0.0, 0.01),
    )
    p.sync_until_converged(max_cycles=120)
    assert snapshot(p.local) == snapshot(p.remote)


def test_bandwidth_throttle_paces_stream():
    """The token-bucket bandwidth fault: a capped direction delivers at
    most rate bytes/s (plus one burst allowance) — the slow-WAN shape the
    snapshot-shipping resume tests lean on."""
    import socket as _socket
    import threading as _threading

    sink = _socket.socket()
    sink.bind(("127.0.0.1", 0))
    sink.listen(1)
    received = {"n": 0}
    done = _threading.Event()

    def drain():
        conn, _ = sink.accept()
        try:
            while True:
                # The proxy hard-closes with RST once the client side goes
                # away; everything forwarded before that still counts.
                chunk = conn.recv(65536)
                if not chunk:
                    break
                received["n"] += len(chunk)
        except OSError:
            pass
        conn.close()
        done.set()

    _threading.Thread(target=drain, daemon=True).start()
    inj = FaultInjector("127.0.0.1", sink.getsockname()[1], seed=5)
    inj.set_faults("c2s", bandwidth_bytes_per_s=32 * 1024)
    try:
        payload = b"x" * (96 * 1024)
        t0 = time.perf_counter()
        s = _socket.create_connection((inj.host, inj.port))
        s.sendall(payload)
        # Half-close: EOF reaches the proxy only after it has drained (and
        # throttled) everything we sent; a full close could RST the stream
        # out from under the pacing loop.
        s.shutdown(_socket.SHUT_WR)
        assert done.wait(timeout=20)
        elapsed = time.perf_counter() - t0
        s.close()
        assert received["n"] == len(payload)
        # 96 KiB at 32 KiB/s with a 32 KiB burst: >= ~2 s on the wire.
        assert elapsed >= 1.5, f"throttle did not pace: {elapsed:.2f}s"
        assert inj.chunks_throttled > 0
    finally:
        inj.close()
        sink.close()

"""Sharded device Merkle plane (ISSUE 12): serving-tree parity and wiring.

ShardedDeviceMerkleState must answer root/TREELEVEL bit-identically to the
CPU golden (and hence single-device) tree at every shard count, through
per-shard-routed incremental scatters and cross-shard restructures; the
mirror/node plumbing must select it via [device] sharding and keep the
PR 11 pump contract (no-flush-on-query) intact. Runs on the virtual
8-device CPU mesh (conftest)."""

import time
import uuid

import numpy as np
import pytest

from merklekv_tpu.merkle.cpu import build_levels
from merklekv_tpu.merkle.encoding import leaf_hash
from merklekv_tpu.parallel.sharded_state import (
    ShardedDeviceMerkleState,
    resolve_shard_count,
)


def _golden_levels(items):
    return build_levels([leaf_hash(k, v) for k, v in sorted(items.items())])


def _golden_root(items):
    return _golden_levels(items)[-1][0].hex() if items else "0" * 64


@pytest.fixture(scope="module", autouse=True)
def _prewarm_jax():
    """Pay the first shard_map compile once, not inside a timed test."""
    st = ShardedDeviceMerkleState.from_items([(b"warm", b"up")], shards=2)
    st.apply([(b"warm", b"again")])
    _ = st.root_hex()


@pytest.mark.parametrize("shards", [1, 2, 8])
def test_build_scatter_restructure_parity(shards):
    items = {b"sp%05d" % i: b"v%d" % i for i in range(133)}
    st = ShardedDeviceMerkleState.from_items(items.items(), shards=shards)
    assert st.shard_count == shards
    assert st.root_hex() == _golden_root(items)

    # Value-only batch STRADDLING shard boundaries: hit the last leaf of
    # one shard and the first of the next, for every boundary.
    skeys = sorted(items)
    l = st._capacity // shards
    batch = {}
    for b in range(1, shards):
        for p in (b * l - 1, b * l):
            if p < len(skeys):
                batch[skeys[p]] = b"x%d" % p
    batch[skeys[0]] = b"first"
    batch[skeys[-1]] = b"last"
    items.update(batch)
    st.apply(list(batch.items()))
    assert st.root_hex() == _golden_root(items)
    assert st.incremental_batches >= 1

    # Structural change crossing shard boundaries (capacity growth).
    changes = []
    for i in range(500, 560):
        items[b"zz%05d" % i] = b"n%d" % i
        changes.append((b"zz%05d" % i, b"n%d" % i))
    del items[b"sp00003"]
    changes.append((b"sp00003", None))
    st.apply(changes)
    assert st.root_hex() == _golden_root(items)
    assert st.structural_batches >= 1


@pytest.mark.parametrize("shards", [2, 8])
def test_level_nodes_parity_every_level(shards):
    items = {b"lv%04d" % i: b"val%d" % i for i in range(97)}
    st = ShardedDeviceMerkleState.from_items(items.items(), shards=shards)
    glv = _golden_levels(items)
    for lvl in range(len(glv)):
        rows, n = st.level_nodes(lvl, 0, len(glv[lvl]))
        assert n == len(items)
        assert [d for _, d in rows] == glv[lvl]
    # Interior slices too (the walk fetches bounded runs, not whole levels).
    rows, _ = st.level_nodes(1, 3, 11)
    assert [d for _, d in rows] == glv[1][3:11]


def test_drain_to_empty_and_refill():
    items = {b"e1": b"a", b"e2": b"b", b"e3": b"c"}
    st = ShardedDeviceMerkleState.from_items(items.items(), shards=8)
    assert st._capacity >= 8  # padded up to the mesh axis
    st.apply([(k, None) for k in items])
    assert st.root_hex() == "0" * 64
    st.apply([(b"back", b"again")])
    assert st.root_hex() == _golden_root({b"back": b"again"})


def test_rebuild_metrics_and_gauge_surface():
    from merklekv_tpu.utils.tracing import get_metrics

    before = get_metrics().snapshot()["counters"].get("device.shard_batches", 0)
    st = ShardedDeviceMerkleState.from_items(
        ((b"m%03d" % i, b"v") for i in range(40)), shards=2
    )
    after = get_metrics().snapshot()["counters"].get("device.shard_batches", 0)
    assert after > before
    assert st.last_shard_rebuild_us >= 0


def test_resolve_shard_count():
    assert resolve_shard_count("off", 8) == 0
    assert resolve_shard_count("auto", 8) == 8
    assert resolve_shard_count("auto", 6) == 4  # largest pow2 subset
    assert resolve_shard_count("auto", 1) == 0  # single device: plain state
    assert resolve_shard_count("2", 8) == 2
    assert resolve_shard_count(4, 8) == 4
    assert resolve_shard_count("1", 8) == 1  # explicit 1 = SPMD over 1 dev
    assert resolve_shard_count("16", 8) == 8  # clamped to the complement
    with pytest.raises(ValueError, match="power-of-two"):
        resolve_shard_count("3", 8)


def test_shard_count_validation():
    with pytest.raises(ValueError, match="power of two"):
        ShardedDeviceMerkleState(shards=3)
    with pytest.raises(ValueError, match="exceeds local device count"):
        ShardedDeviceMerkleState(shards=16)


def test_config_sharding_values(tmp_path):
    from merklekv_tpu.config import Config

    assert Config().device.sharding == "off"
    p = tmp_path / "c.toml"
    p.write_text("[device]\nsharding = \"auto\"\n")
    assert Config.load(str(p)).device.sharding == "auto"
    p.write_text("[device]\nsharding = 4\n")
    assert Config.load(str(p)).device.sharding == "4"
    # Deprecated alias promotes to auto.
    p.write_text("[device]\nsharded_mirror = true\n")
    assert Config.load(str(p)).device.sharding == "auto"
    p.write_text("[device]\nsharding = 3\n")
    with pytest.raises(ValueError, match="power-of-two"):
        Config.load(str(p))


def test_divergence_engine_boundary_parity():
    """The N-replica diff routed through the sharded SPMD program must be
    bit-identical to the host twin, including a key axis that does not
    divide the mesh (padded with absent columns)."""
    from merklekv_tpu.merkle.diff import (
        divergence_masks_engine,
        divergence_masks_np,
    )

    rng = np.random.RandomState(7)
    for n in (64, 77):  # 77: pad path (not divisible by the 8-way mesh)
        dig = np.tile(
            rng.randint(0, 2**32, size=(1, n, 8), dtype=np.uint64).astype(
                np.uint32
            ),
            (5, 1, 1),
        )
        pres = np.ones((5, n), bool)
        dig[2, rng.randint(0, n, size=4)] ^= 9
        pres[3, rng.randint(0, n, size=3)] = False
        golden = divergence_masks_np(dig, pres)
        routed = np.asarray(divergence_masks_engine(dig, pres, min_keys=0))
        assert np.array_equal(routed, golden)
    # Above-threshold default path stays callable (single-device route for
    # small n when min_keys is left at the default).
    small = np.asarray(
        divergence_masks_engine(dig[:, :16], pres[:, :16])
    )
    assert np.array_equal(small, divergence_masks_np(dig[:, :16], pres[:, :16]))


def test_mirror_sharded_backend_and_pump_contract():
    """DeviceTreeMirror with [device] sharding=8 serves the pump-published
    snapshot from the sharded state — bit-identical to the engine root —
    and the no-flush-on-query invariant holds (published reads never drain
    staged work)."""
    from merklekv_tpu.cluster.mirror import DeviceTreeMirror
    from merklekv_tpu.native_bindings import NativeEngine

    engine = NativeEngine("mem")
    try:
        for i in range(64):
            engine.set(b"mk%03d" % i, b"v%d" % i)
        mirror = DeviceTreeMirror(engine, sharding="8")
        try:
            mirror.start_warming()
            deadline = time.time() + 60
            while time.time() < deadline and not mirror.ready():
                time.sleep(0.02)
            assert mirror.ready(), "sharded mirror never warmed"
            assert mirror.shard_count() == 8
            assert mirror.published_root_hex() == engine.merkle_root().hex()
            # Stage a write; the published snapshot must NOT move until the
            # pump publishes (no-flush-on-query).
            engine.set(b"mk000", b"updated")
            from merklekv_tpu.cluster.change_event import ChangeEvent, OpKind

            ev = ChangeEvent(
                op=OpKind.SET, key="mk000", val=b"updated",
                ts=time.time_ns(), src="test",
            )
            gen_before = mirror._published_gen
            mirror.on_events([ev], watermark=engine.version())
            _ = mirror.published_root_hex()  # read-only serve
            mirror.publish_now()
            assert mirror.published_root_hex() == engine.merkle_root().hex()
            assert mirror._published_gen > gen_before
            assert mirror.shard_rebuild_us() >= 0
        finally:
            mirror.close()
    finally:
        engine.close()


def test_cluster_node_metrics_lines_with_sharding():
    """End-to-end [device] sharding=2 node: HASH serves the sharded tree
    and METRICS carries the device.shards line."""
    from merklekv_tpu.client import MerkleKVClient
    from merklekv_tpu.cluster.node import ClusterNode
    from merklekv_tpu.cluster.transport import TcpBroker
    from merklekv_tpu.config import Config
    from merklekv_tpu.native_bindings import NativeEngine, NativeServer

    broker = TcpBroker()
    engine = NativeEngine("mem")
    server = NativeServer(engine, "127.0.0.1", 0)
    server.start()
    cfg = Config()
    cfg.replication.enabled = True
    cfg.replication.mqtt_broker = broker.host
    cfg.replication.mqtt_port = broker.port
    cfg.replication.topic_prefix = f"shardp-{uuid.uuid4().hex[:8]}"
    cfg.replication.client_id = "sp1"
    cfg.device.sharding = "2"
    node = ClusterNode(cfg, engine, server)
    node.start()
    client = MerkleKVClient("127.0.0.1", server.port, timeout=30.0).connect()
    try:
        for i in range(40):
            client.set(f"spk{i:03d}", f"val{i}")
        native_root = engine.merkle_root().hex()
        client.hash()  # trigger warming
        deadline = time.time() + 60
        while time.time() < deadline:
            if node._mirror is not None and node._mirror.ready():
                break
            time.sleep(0.02)
        assert node._mirror.ready(), "mirror never warmed"
        assert node._mirror.shard_count() == 2
        assert node.device_root_hex(force=True) == native_root
        metrics = client.metrics()
        assert metrics.get("device.shards") == "2"
    finally:
        client.close()
        node.stop()
        server.close()
        engine.close()
        broker.close()

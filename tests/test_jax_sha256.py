"""Golden tests: JAX SHA-256 vs hashlib, over the packing pipeline."""

import hashlib

import numpy as np
import pytest

from merklekv_tpu.merkle.packing import pack_leaves
from merklekv_tpu.ops.sha256 import (
    digests_to_bytes,
    sha256_blocks,
    sha256_node_pairs,
    sha256_single_block,
)
from merklekv_tpu.merkle.encoding import encode_leaf, leaf_hash, node_hash


def _ref_digest(msg: bytes) -> bytes:
    return hashlib.sha256(msg).digest()


def _pack_one(msg: bytes):
    """Pad an arbitrary message into SHA-256 blocks (test-local helper)."""
    mlen = len(msg)
    nb = (mlen + 9 + 63) // 64
    buf = np.zeros(nb * 64, np.uint8)
    buf[:mlen] = np.frombuffer(msg, np.uint8)
    buf[mlen] = 0x80
    buf[-8:] = np.frombuffer(np.array([mlen * 8], ">u8").tobytes(), np.uint8)
    return buf.view(">u4").astype(np.uint32).reshape(1, nb, 16), np.array(
        [nb], np.int32
    )


@pytest.mark.parametrize(
    "msg",
    [
        b"",
        b"abc",
        b"a" * 55,  # max single-block payload
        b"a" * 56,  # first length that spills to two blocks
        b"a" * 63,
        b"a" * 64,
        b"a" * 119,
        b"a" * 120,
        b"hello world" * 30,  # 330 bytes, 6 blocks
        bytes(range(256)),
    ],
)
def test_sha256_blocks_matches_hashlib(msg):
    blocks, nb = _pack_one(msg)
    got = digests_to_bytes(sha256_blocks(blocks, nb))[0]
    assert got == _ref_digest(msg)


def test_sha256_single_block():
    msg = b"abc"
    blocks, _ = _pack_one(msg)
    got = digests_to_bytes(sha256_single_block(blocks[:, 0, :]))[0]
    assert got == _ref_digest(msg)


def test_mixed_length_batch():
    rng = np.random.default_rng(7)
    msgs = [rng.bytes(int(n)) for n in rng.integers(0, 200, size=64)]
    max_b = max((len(m) + 9 + 63) // 64 for m in msgs)
    blocks = np.zeros((len(msgs), max_b, 16), np.uint32)
    nbs = np.zeros(len(msgs), np.int32)
    for i, m in enumerate(msgs):
        b, nb = _pack_one(m)
        blocks[i, : b.shape[1]] = b[0]
        nbs[i] = nb[0]
    got = digests_to_bytes(sha256_blocks(blocks, nbs))
    for g, m in zip(got, msgs):
        assert g == _ref_digest(m)


def test_node_pairs_matches_cpu_spec():
    rng = np.random.default_rng(3)
    lefts = [rng.bytes(32) for _ in range(17)]
    rights = [rng.bytes(32) for _ in range(17)]
    l = np.stack([np.frombuffer(b, ">u4").astype(np.uint32) for b in lefts])
    r = np.stack([np.frombuffer(b, ">u4").astype(np.uint32) for b in rights])
    got = digests_to_bytes(sha256_node_pairs(l, r))
    for g, lb, rb in zip(got, lefts, rights):
        assert g == node_hash(lb, rb)


def test_pack_leaves_matches_encode_leaf():
    rng = np.random.default_rng(11)
    keys, values = [], []
    for n in range(40):
        keys.append(rng.bytes(int(rng.integers(0, 80))))
        values.append(rng.bytes(int(rng.integers(0, 150))))
    keys += [b"", "héllo\x00".encode(), b"k"]
    values += [b"", b"v", "é世界".encode()]
    packed = pack_leaves(keys, values)
    got = digests_to_bytes(sha256_blocks(packed.blocks, packed.nblocks))
    for g, k, v in zip(got, keys, values):
        assert g == _ref_digest(encode_leaf(k, v))
        assert g == leaf_hash(k, v)


def test_pack_leaves_empty():
    packed = pack_leaves([], [])
    assert packed.n == 0

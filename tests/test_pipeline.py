"""Pipelined framing over the epoll worker-pool I/O plane (ISSUE 9).

The server parses ALL complete frames per readable event, carries partial
frames across reads (and across worker wakeups), dispatches them in
order, and flushes coalesced responses with one writev per burst. These
tests pin the wire-visible contract:

- responses arrive complete, in request order, byte-identical to serial
  dispatch, for a multi-command pipeline split at EVERY byte boundary
  across successive sends;
- a stalled (never-reading) connection does not stall its worker's other
  connections — backpressure parks the slow one, the rest keep serving;
- the compat mode (``pipelined=False``) and a single-loop pool
  (``io_threads=1``) answer the same bytes;
- the per-worker loop counters surface on STATS.
"""

import socket
import time

import pytest

from merklekv_tpu.client import MerkleKVClient
from merklekv_tpu.native_bindings import NativeEngine, NativeServer


@pytest.fixture
def pooled():
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0)
    srv.start()
    yield eng, srv
    srv.close()
    eng.close()


@pytest.fixture
def single_loop():
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0, io_threads=1)
    srv.start()
    yield eng, srv
    srv.close()
    eng.close()


def _pipeline_commands(prefix: str) -> tuple[list[bytes], list[bytes]]:
    """A deterministic command sequence under a fresh key prefix and the
    exact per-command response bytes serial dispatch produces — single-
    AND multi-line responses, values with spaces, errors, misses."""
    p = prefix.encode()
    return (
        [
            b"SET " + p + b":a v1",
            b"GET " + p + b":a",
            b"GET " + p + b":missing",
            b"SET " + p + b":b w x  y",
            b"GET " + p + b":b",
            b"MGET " + p + b":a " + p + b":b " + p + b":nope",
            b"INC " + p + b":n 5",
            b"EXISTS " + p + b":a " + p + b":b " + p + b":missing",
            b"PING hello",
            b"DEL " + p + b":a",
            b"GET " + p + b":a",
            b"BOGUSVERB zzz",
            b"APPEND " + p + b":b !",
        ],
        [
            b"OK\r\n",
            b"VALUE v1\r\n",
            b"NOT_FOUND\r\n",
            b"OK\r\n",
            b"VALUE w x  y\r\n",
            b"VALUES 2\r\n"
            + p + b":a v1\r\n"
            + p + b":b w x  y\r\n"
            + p + b":nope NOT_FOUND\r\n",
            b"VALUE 5\r\n",
            b"EXISTS 2\r\n",
            b"PONG hello\r\n",
            b"DELETED\r\n",
            b"NOT_FOUND\r\n",
            b"ERROR Unknown command: BOGUSVERB\r\n",
            b"VALUE w x  y!\r\n",
        ],
    )


def _pipeline_script(prefix: str) -> tuple[bytes, bytes]:
    cmds, resps = _pipeline_commands(prefix)
    return b"".join(c + b"\r\n" for c in cmds), b"".join(resps)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    data = b""
    while len(data) < n:
        chunk = sock.recv(n - len(data))
        if not chunk:
            break
        data += chunk
    return data


def _run_split(sock: socket.socket, payload: bytes, expect: bytes,
               cut: int, settle: bool) -> None:
    sock.sendall(payload[:cut])
    if settle:
        # Give the worker a wakeup with only the first fragment buffered,
        # so the partial frame genuinely carries across epoll turns.
        time.sleep(0.002)
    sock.sendall(payload[cut:])
    got = _recv_exact(sock, len(expect))
    assert got == expect, (
        f"cut={cut}: responses diverged\n got={got!r}\nwant={expect!r}"
    )


def test_pipeline_split_at_every_byte_boundary(pooled):
    """The full script, split into two sends at every byte offset: the
    response stream must be byte-identical to serial dispatch each time.
    A sparse subset of cuts sleeps between fragments to force the split
    across separate worker wakeups (every-cut sleeps would take minutes);
    TCP segmentation exercises the rest."""
    _, srv = pooled
    with socket.create_connection(("127.0.0.1", srv.port), timeout=15) as s:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        payload0, _ = _pipeline_script("cut0000")
        for cut in range(len(payload0) + 1):
            # Fixed-width prefix keeps every iteration's payload the same
            # length, so `cut` really sweeps every byte boundary.
            payload, expect = _pipeline_script(f"cut{cut:04d}")
            assert len(payload) == len(payload0)
            _run_split(s, payload, expect, cut, settle=(cut % 17 == 0))


def test_pipeline_fragmented_random_splits(pooled):
    """Seeded random multi-fragment splits (3..8 sends) of a LONG pipeline
    (the byte-boundary test covers two-fragment cuts exhaustively)."""
    import random

    _, srv = pooled
    rng = random.Random(1234)
    with socket.create_connection(("127.0.0.1", srv.port), timeout=15) as s:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        for round_no in range(20):
            parts = []
            expects = []
            for j in range(6):  # 6 scripts back-to-back = 78 commands
                pl, ex = _pipeline_script(f"rf{round_no}x{j}")
                parts.append(pl)
                expects.append(ex)
            payload, expect = b"".join(parts), b"".join(expects)
            cuts = sorted(
                rng.sample(range(1, len(payload)), rng.randint(2, 7))
            )
            frags = [
                payload[a:b]
                for a, b in zip([0] + cuts, cuts + [len(payload)])
            ]
            for k, frag in enumerate(frags):
                s.sendall(frag)
                if k % 2 == 0:
                    time.sleep(0.001)
            got = _recv_exact(s, len(expect))
            assert got == expect


def test_serial_vs_pipelined_byte_identical(single_loop):
    """The same script answered serially (one command per round trip) and
    pipelined (one send) produces identical concatenated bytes — and the
    single-loop pool behaves like the wide one."""
    _, srv = single_loop
    cmds, resps = _pipeline_commands("serial")
    serial = b""
    with socket.create_connection(("127.0.0.1", srv.port), timeout=15) as s:
        for cmd, resp in zip(cmds, resps):
            s.sendall(cmd + b"\r\n")
            serial += _recv_exact(s, len(resp))
    assert serial == b"".join(resps)
    # The same mutations are not idempotent, so the pipelined pass runs
    # under a fresh prefix on a fresh connection.
    payload2, expect2 = _pipeline_script("piped")
    with socket.create_connection(("127.0.0.1", srv.port), timeout=15) as s:
        s.sendall(payload2)
        got = _recv_exact(s, len(expect2))
    assert got == expect2


def test_slow_reader_does_not_stall_worker(single_loop):
    """One connection queues megabytes of GET responses and never reads;
    with a SINGLE worker loop, a second connection must keep getting
    answers promptly (EAGAIN-aware write parking + read backpressure),
    and the stalled connection must still receive every byte once it
    starts reading."""
    eng, srv = single_loop
    big = b"B" * (128 * 1024)
    eng.set(b"big", big)
    n_gets = 128  # 128 x ~128KiB = ~16 MiB of queued responses
    one_resp = len(b"VALUE " + big + b"\r\n")

    slow = socket.create_connection(("127.0.0.1", srv.port), timeout=60)
    fast = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
    try:
        slow.sendall(b"GET big\r\n" * n_gets)
        time.sleep(0.05)  # let the worker hit the backlog watermark
        # The same worker must keep serving the other connection with
        # round trips in the microsecond-to-millisecond league.
        t0 = time.perf_counter()
        for i in range(50):
            fast.sendall(b"PING alive%d\r\n" % i)
            line = b""
            while not line.endswith(b"\r\n"):
                line += fast.recv(256)
            assert line == b"PONG alive%d\r\n" % i
        elapsed = time.perf_counter() - t0
        assert elapsed < 2.0, f"sibling connection stalled: {elapsed:.3f}s"
        # Now drain the slow connection: all n_gets responses, complete.
        total = one_resp * n_gets
        got = 0
        buf = bytearray(1 << 16)
        while got < total:
            n = slow.recv_into(buf)
            assert n > 0, "server closed the stalled connection early"
            got += n
        assert got == total
    finally:
        slow.close()
        fast.close()


def test_half_close_still_answers_buffered_burst(pooled):
    """A client that pipelines a burst and immediately shuts down its
    WRITE side (FIN) must still get every response: commands that arrived
    before the FIN are dispatched and their responses flushed before the
    server closes."""
    _, srv = pooled
    payload, expect = _pipeline_script("halfclose")
    with socket.create_connection(("127.0.0.1", srv.port), timeout=15) as s:
        s.sendall(payload)
        s.shutdown(socket.SHUT_WR)
        got = _recv_exact(s, len(expect))
        assert got == expect
        assert s.recv(1024) == b""  # then the server closes


def test_compat_mode_answers_identical_bytes():
    """pipelined=False (the bench's A/B baseline: one write per response)
    must still answer a pipelined burst completely and in order."""
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0, io_threads=1, pipelined=False)
    srv.start()
    try:
        payload, expect = _pipeline_script("compat")
        with socket.create_connection(
            ("127.0.0.1", srv.port), timeout=15
        ) as s:
            s.sendall(payload)
            got = _recv_exact(s, len(expect))
        assert got == expect
    finally:
        srv.close()
        eng.close()


def test_io_worker_stats_surface(pooled):
    """STATS carries the io-plane lines: pool shape + per-worker loop
    counters, integer-valued, commands summing to total dispatches."""
    _, srv = pooled
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        for i in range(20):
            c.set(f"ws:{i}", "v")
        stats = c.stats()
    n = int(stats["io_threads"])
    assert n >= 1 and n == srv.io_threads
    assert stats["io_pipelined"] == "1"
    fields = ("connections", "commands", "wakeups", "writev_calls",
              "writev_bytes")
    for i in range(n):
        for f in fields:
            assert f"io_worker_{i}_{f}" in stats, (i, f)
            int(stats[f"io_worker_{i}_{f}"])  # integer-valued
    total_worker_cmds = sum(
        int(stats[f"io_worker_{i}_commands"]) for i in range(n)
    )
    # The STATS dispatch snapshots itself BEFORE its own worker counter
    # bumps, so the 20 SETs are the guaranteed floor.
    assert total_worker_cmds >= 20


def test_io_threads_config_respected():
    """An explicit io_threads width is resolved exactly."""
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0, io_threads=3)
    srv.start()
    try:
        assert srv.io_threads == 3
        with MerkleKVClient("127.0.0.1", srv.port) as c:
            assert int(c.stats()["io_threads"]) == 3
    finally:
        srv.close()
        eng.close()


def test_many_connections_pipelined_all_complete(pooled):
    """64 connections x pipelined bursts against the pool: every response
    accounted for on every connection (the bench scenario's correctness
    core, shrunk to tier-1 size)."""
    eng, srv = pooled
    for i in range(256):
        eng.set(b"mk:%03d" % i, b"val-%03d" % i)
    depth = 32
    conns = []
    try:
        for _ in range(64):
            conns.append(
                socket.create_connection(("127.0.0.1", srv.port), timeout=30)
            )
        for rounds in range(3):
            for ci, s in enumerate(conns):
                burst = b"".join(
                    b"GET mk:%03d\r\n" % ((ci * 7 + j) % 256)
                    for j in range(depth)
                )
                s.sendall(burst)
            for ci, s in enumerate(conns):
                expect = b"".join(
                    b"VALUE val-%03d\r\n" % ((ci * 7 + j) % 256)
                    for j in range(depth)
                )
                got = _recv_exact(s, len(expect))
                assert got == expect, f"conn {ci} round {rounds}"
    finally:
        for s in conns:
            s.close()

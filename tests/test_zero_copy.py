"""Zero-copy serving path: refcounted value slabs + reuseport accept
sharding (ISSUE 14).

Covers the tentpole invariants — a served block's lifetime survives
DEL/overwrite of its key (slow-reader pin), slab accounting counts
reader-pinned bytes so the memory watermarks stay honest, slab-arena
exhaustion sheds with a typed retryable BUSY, Merkle roots are
bit-identical across the zero-copy/compat A/B — plus the accept-shard
distribution contract and the client-side max_value_bytes fix.
"""

import asyncio
import socket
import time

import pytest

from merklekv_tpu.client import (
    AsyncMerkleKVClient,
    MerkleKVClient,
    ServerBusyError,
)
from merklekv_tpu.config import Config
from merklekv_tpu.native_bindings import NativeEngine, NativeServer


def _wait(pred, timeout=5.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ----------------------------------------------------------- slab basics


def test_slab_accounting_tracks_engine_state():
    with NativeEngine("mem") as eng:
        assert eng.slab_stats() == {
            "bytes": 0, "blocks": 0, "pinned_bytes": 0, "allocs": 0,
            "alloc_failures": 0,
        }
        eng.set(b"a", b"x" * 1000)
        eng.set(b"b", b"y" * 500)
        st = eng.slab_stats()
        assert st["bytes"] == 1500
        assert st["blocks"] == 2
        assert st["pinned_bytes"] == 0
        assert st["allocs"] == 2
        # Overwrite replaces the block; DEL frees it.
        eng.set(b"a", b"z" * 10)
        assert eng.slab_stats()["bytes"] == 510
        eng.delete(b"b")
        st = eng.slab_stats()
        assert st["bytes"] == 10 and st["blocks"] == 1
        # memory_usage = key bytes + slab bytes.
        assert eng.memory_usage() == 1 + 10
        eng.truncate()
        assert eng.slab_stats()["bytes"] == 0
        assert eng.memory_usage() == 0


def test_log_engine_delegates_slab_stats(tmp_path):
    with NativeEngine("log", str(tmp_path / "d")) as eng:
        eng.set(b"k", b"v" * 256)
        assert eng.slab_stats()["bytes"] == 256


# ------------------------------------------------- wire parity + A/B root


@pytest.fixture
def zc_pair():
    """One pre-seeded engine served by a zero-copy server and a compat
    (zero_copy=False) server at once — the A/B surface."""
    eng = NativeEngine("mem")
    zc = NativeServer(eng, "127.0.0.1", 0, max_line=4 << 20)
    compat = NativeServer(
        eng, "127.0.0.1", 0, zero_copy=False, max_line=4 << 20
    )
    zc.start()
    compat.start()
    yield eng, zc, compat
    compat.close()
    zc.close()
    eng.close()


def test_wire_identical_and_root_identical_across_ab(zc_pair):
    eng, zc, compat = zc_pair
    vals = {
        "small": "s",
        "mid": "m" * 600,              # > inline threshold: block segment
        "big": "B" * (256 << 10),
    }
    with MerkleKVClient("127.0.0.1", zc.port) as a, MerkleKVClient(
        "127.0.0.1", compat.port
    ) as b:
        for k, v in vals.items():
            a.set(k, v)
        for k, v in vals.items():
            assert a.get(k) == v, k
            assert b.get(k) == v, k
        assert a.mget(list(vals)) == b.mget(list(vals)) == vals
        assert a.get("missing") is None
        # Bit-identical Merkle root across the serve paths.
        assert a.hash() == b.hash()
        sa = a.stats()
        sb = b.stats()
        assert int(sa["serve_zero_copy"]) >= 2  # mid + big
        assert int(sa["serve_value_copies"]) == 0
        assert int(sb["serve_value_copies"]) >= 2
        assert int(sb["serve_zero_copy"]) == 0


# ------------------------------------------------------- slow-reader pin


def test_slow_reader_pins_values_across_del_overwrite_and_evict():
    """Park 16 MiB of large values behind EPOLLOUT, then overwrite, DEL
    and tombstone-evict the keys: every parked byte must arrive intact
    (the response pins the value version at dispatch time), the pinned
    bytes must stay visible to memory_usage(), and the slab must release
    once the reader drains."""
    n_keys, size = 16, 1 << 20
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0, io_threads=2)
    srv.start()
    try:
        patterns = {
            i: bytes([97 + i % 26]) * size for i in range(n_keys)
        }
        for i, pat in patterns.items():
            eng.set(b"pin:%d" % i, pat)
        base = eng.slab_stats()
        assert base["bytes"] == n_keys * size

        # Two parked readers, 8 MiB each (the per-connection output
        # backlog caps at the kOutHigh backpressure watermark, 8 MiB, by
        # design — 16 MiB parks across two conns). Tiny receive buffers
        # (set BEFORE connect so the window honors them) keep the kernel
        # from absorbing the responses.
        socks = []
        for half in range(2):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 16384)
            s.settimeout(30)
            s.connect(("127.0.0.1", srv.port))
            s.sendall(
                b"".join(
                    b"GET pin:%d\r\n" % i
                    for i in range(half * 8, half * 8 + 8)
                )
            )
            socks.append(s)

        with MerkleKVClient("127.0.0.1", srv.port) as c:
            # Every GET dispatched (responses staged, refs taken) before
            # the churn begins.
            assert _wait(
                lambda: int(c.stats().get("serve_zero_copy", 0)) >= n_keys
            ), c.stats().get("serve_zero_copy")
            # Churn the keys while the reader is parked: overwrite a
            # third, DEL the rest (tombstones).
            for i in range(n_keys):
                if i % 3 == 0:
                    c.set(f"pin:{i}", "tiny")
                else:
                    c.delete(f"pin:{i}")
        # The engine dropped its refs: the old blocks are now pinned ONLY
        # by the parked responses — and still counted by memory_usage so
        # the watermarks see them.
        assert _wait(
            lambda: eng.slab_stats()["pinned_bytes"] >= 8 * size
        ), eng.slab_stats()
        st = eng.slab_stats()
        assert eng.memory_usage() >= st["pinned_bytes"]

        # Drain: every parked byte must be the ORIGINAL value bytes.
        for half, s in enumerate(socks):
            buf = bytearray()
            while buf.count(b"\n") < 8:
                chunk = s.recv(1 << 18)
                assert chunk, "server closed mid-drain"
                buf.extend(chunk)
            lines = bytes(buf).split(b"\r\n")
            for j in range(8):
                i = half * 8 + j
                assert lines[j] == b"VALUE " + patterns[i], (
                    f"pin:{i} corrupt"
                )
            s.close()

        # After the drain the pins release: only the overwritten tiny
        # values remain in the slab.
        live = sum(4 for i in range(n_keys) if i % 3 == 0)
        assert _wait(
            lambda: eng.slab_stats()["bytes"] == live
            and eng.slab_stats()["pinned_bytes"] == 0
        ), eng.slab_stats()
    finally:
        srv.close()
        eng.close()


# ------------------------------------------------------ arena exhaustion


def test_slab_exhaustion_sheds_with_busy_memory(monkeypatch):
    monkeypatch.setenv("MKV_MAX_SLAB_BYTES", str(1 << 20))
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0, max_line=4 << 20)
    srv.start()
    try:
        with MerkleKVClient("127.0.0.1", srv.port) as c:
            c.set("a", "x" * (512 << 10))
            # Second write would cross the 1 MiB arena: typed retryable
            # BUSY, never an abort/OOM.
            with pytest.raises(ServerBusyError, match="memory"):
                c.set("b", "y" * (700 << 10))
            # APPEND past the limit sheds the same way.
            with pytest.raises(ServerBusyError, match="memory"):
                c.append("a", "z" * (700 << 10))
            assert eng.slab_stats()["alloc_failures"] >= 2
            # The shed is recoverable: free space, retry, it lands.
            assert c.delete("a") is True
            c.set("b", "y" * (700 << 10))
            assert len(c.get("b")) == 700 << 10
            st = c.stats()
            assert int(st["slab_alloc_failures"]) >= 2
            assert int(st["shed_commands"]) >= 2
    finally:
        srv.close()
        eng.close()


def test_slab_exhaustion_engine_level(monkeypatch):
    monkeypatch.setenv("MKV_MAX_SLAB_BYTES", "1000")
    from merklekv_tpu.native_bindings import NativeError

    with NativeEngine("mem") as eng:
        eng.set(b"a", b"x" * 900)
        with pytest.raises(NativeError):
            eng.set(b"b", b"y" * 200)
        assert eng.slab_stats()["alloc_failures"] == 1
        eng.delete(b"a")
        eng.set(b"b", b"y" * 200)  # recovers


def test_overwrite_near_arena_limit_is_admitted(monkeypatch):
    """Overwriting (or shrinking) an existing key must not be refused by
    the arena cap: the replaced value's bytes credit the limit check, so
    the retryable BUSY is never handed out for a write no retry could
    ever satisfy (the old value only leaves the account on install)."""
    monkeypatch.setenv("MKV_MAX_SLAB_BYTES", "1000")
    from merklekv_tpu.native_bindings import NativeError

    with NativeEngine("mem") as eng:
        eng.set(b"a", b"x" * 900)
        eng.set(b"a", b"y" * 200)   # shrink: would double-charge w/o credit
        eng.set(b"a", b"z" * 900)   # same-size class overwrite admitted
        assert eng.get(b"a") == b"z" * 900
        # A genuinely NEW key past the cap still sheds.
        with pytest.raises(NativeError):
            eng.set(b"b", b"w" * 200)
        assert eng.slab_stats()["alloc_failures"] == 1


# ------------------------------------------------- accept-shard contract


def _worker_accepts(stats: dict) -> dict:
    return {
        k: int(v) for k, v in stats.items()
        if k.startswith("io_worker_") and k.endswith("_accepts")
    }


def test_reuseport_distributes_accepts_across_workers():
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0, io_threads=4, reuseport="on")
    srv.start()
    try:
        assert srv.reuseport is True
        conns = [
            MerkleKVClient("127.0.0.1", srv.port).connect()
            for _ in range(48)
        ]
        for c in conns:
            assert c.ping().startswith("PONG")
        stats = conns[0].stats()
        assert stats["io_reuseport"] == "1"
        accepts = _worker_accepts(stats)
        assert len(accepts) == 4
        # The kernel deals across the worker listeners (the primary
        # accept loop keeps its own share): with 48 conns over 5 sockets,
        # at least two workers must have accepted directly.
        assert sum(accepts.values()) > 0
        assert sum(1 for v in accepts.values() if v > 0) >= 2, accepts
        for c in conns:
            c.close()
    finally:
        srv.close()
        eng.close()


def test_reuseport_off_single_loop_parity():
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0, io_threads=4, reuseport="off")
    srv.start()
    try:
        assert srv.reuseport is False
        conns = [
            MerkleKVClient("127.0.0.1", srv.port).connect()
            for _ in range(12)
        ]
        for c in conns:
            assert c.ping().startswith("PONG")
        stats = conns[0].stats()
        assert stats["io_reuseport"] == "0"
        # Single accept loop: no worker ever accepts on its own listener,
        # yet every connection is served (round-robin handoff parity).
        assert all(v == 0 for v in _worker_accepts(stats).values())
        for c in conns:
            c.close()
    finally:
        srv.close()
        eng.close()


def test_reuseport_admission_control_shared_count():
    """max_connections holds across BOTH accept paths: the shared atomic
    count gates worker-listener accepts exactly like the classic loop."""
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0, io_threads=2, reuseport="on")
    srv.start()
    srv.set_limits(max_connections=4)
    try:
        keep = [
            MerkleKVClient("127.0.0.1", srv.port).connect()
            for _ in range(4)
        ]
        for c in keep:
            c.ping()
        refused = 0
        for _ in range(8):
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            s.settimeout(5)
            try:
                data = s.recv(256)
            except (TimeoutError, OSError):
                data = b""
            if b"BUSY connections" in data:
                refused += 1
            s.close()
        assert refused >= 7  # all excess accepts answered BUSY
        for c in keep:
            c.close()
    finally:
        srv.close()
        eng.close()


# ------------------------------------------ client max_value_bytes fix


@pytest.fixture
def big_value_server():
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0, max_line=8 << 20)
    srv.start()
    yield srv
    srv.close()
    eng.close()


def test_sync_client_round_trips_1mib_value(big_value_server):
    val = "v" * (1 << 20)
    with MerkleKVClient(
        "127.0.0.1", big_value_server.port, max_value_bytes=1 << 20
    ) as c:
        c.set("big", val)
        assert c.get("big") == val


def test_async_client_round_trips_1mib_value(big_value_server):
    """The old fixed 1 MiB StreamReader limit made a ~1 MiB GET raise a
    bare ValueError mid-stream; the limit now sizes from
    max_value_bytes (default covers exactly this boundary)."""
    val = "v" * (1 << 20)

    async def run():
        async with AsyncMerkleKVClient(
            "127.0.0.1", big_value_server.port
        ) as c:
            await c.set("big", val)
            return await c.get("big")

    assert asyncio.run(run()) == val


def test_sync_client_enforces_max_value_bytes(big_value_server):
    """max_value_bytes bounds the sync reader too (async parity): an
    oversized VALUE line is refused with a typed ProtocolError naming
    the knob, not buffered without bound or a bare ValueError."""
    from merklekv_tpu.client import ConnectionError as MkvConnectionError
    from merklekv_tpu.client import ProtocolError

    val = "w" * (3 << 20)
    with MerkleKVClient(
        "127.0.0.1", big_value_server.port, max_value_bytes=4 << 20
    ) as writer:
        writer.set("big3", val)
        assert writer.get("big3") == val  # large enough limit: fine
    with MerkleKVClient(
        "127.0.0.1", big_value_server.port, max_value_bytes=1 << 20
    ) as reader:
        with pytest.raises(ProtocolError, match="max_value_bytes"):
            reader.get("big3")
        # The stream was mid-value, hence desynchronized: the client must
        # close rather than serve value bytes as later responses.
        with pytest.raises(MkvConnectionError, match="not connected"):
            reader.get("anything")


def test_async_client_larger_max_value_bytes(big_value_server):
    val = "w" * (3 << 20)

    async def run():
        async with AsyncMerkleKVClient(
            "127.0.0.1",
            big_value_server.port,
            max_value_bytes=4 << 20,
        ) as c:
            await c.set("big3", val)
            return await c.get("big3")

    assert asyncio.run(run()) == val


# ------------------------------------------------------ config + metrics


def test_server_config_parses_zero_copy_knobs():
    cfg = Config.from_dict(
        {
            "server": {
                "reuseport": "off",
                "zero_copy": False,
                "max_line_bytes": 4 << 20,
            }
        }
    )
    assert cfg.server.reuseport == "off"
    assert cfg.server.zero_copy is False
    assert cfg.server.max_line_bytes == 4 << 20
    with pytest.raises(ValueError, match="reuseport"):
        Config.from_dict({"server": {"reuseport": "sometimes"}})
    with pytest.raises(ValueError, match="max_line_bytes"):
        Config.from_dict({"server": {"max_line_bytes": -1}})


def test_exporter_bridges_slab_and_accept_families():
    from merklekv_tpu.obs.exporter import render_prometheus

    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0, io_threads=2, reuseport="auto")
    srv.start()
    try:
        eng.set(b"k", b"v" * 1000)
        body = render_prometheus(stats_text=srv.stats_text())
        assert "mkv_native_slab_bytes 1000" in body
        assert "mkv_native_slab_blocks 1" in body
        assert "mkv_native_slab_pinned_bytes 0" in body
        assert "# TYPE mkv_native_serve_zero_copy counter" in body
        assert "# TYPE mkv_native_slab_alloc_failures counter" in body
        assert "mkv_native_io_reuseport" in body
        assert 'mkv_native_io_worker_accepts{worker="0"}' in body
    finally:
        srv.close()
        eng.close()


def test_top_parses_served_bytes():
    from merklekv_tpu.obs.top import NodeSample, render_table

    s = NodeSample(node="n1:7379")
    s.ok = True
    s.served_bytes = 0
    prev = NodeSample(node="n1:7379")
    prev.ok = True
    out = render_table({"n1:7379": prev}, {"n1:7379": s})
    assert "SRV_MB/S" in out

"""ChangeEvent codecs + pure LWW applier (no transport).

Mirrors the reference's codec roundtrip tests and its LocalApplier fake
(change_event.rs:194-460): idempotency, LWW, deterministic ts tie-break —
tested as pure functions against a plain dict store.
"""

import pytest

from merklekv_tpu.cluster import (
    ChangeEvent,
    LWWApplier,
    OpKind,
    decode_any,
    decode_binary,
    decode_cbor,
    decode_json,
    encode_binary,
    encode_cbor,
    encode_json,
)


def ev(**kw) -> ChangeEvent:
    base = dict(op=OpKind.SET, key="k", val=b"v", ts=100, src="n1")
    base.update(kw)
    return ChangeEvent(**base)


# ------------------------------------------------------------------ codecs

@pytest.mark.parametrize(
    "enc,dec",
    [(encode_cbor, decode_cbor), (encode_binary, decode_binary),
     (encode_json, decode_json)],
)
def test_roundtrip_all_codecs(enc, dec):
    for e in [
        ev(),
        ev(op=OpKind.DEL, val=None),
        ev(op=OpKind.INCR, val=b"42"),
        ev(val=b"\x00\xff binary \t bytes"),
        ev(prev=b"\xab" * 32, ttl=3600),
        ev(key="unicode-ключ-☃", src="node-β"),
        ev(ts=2**63 + 5),  # > i64: u64 range must survive
    ]:
        assert dec(enc(e)) == e


def test_decode_any_tries_all():
    e = ev()
    assert decode_any(encode_cbor(e)) == e
    assert decode_any(encode_binary(e)) == e
    assert decode_any(encode_json(e)) == e
    with pytest.raises(ValueError):
        decode_any(b"\x00garbage not an event")
    with pytest.raises(ValueError):
        decode_any(b"")


def test_cbor_is_standard_subset():
    # A well-formed RFC 8949 map readable by any CBOR decoder: major 5 head.
    data = encode_cbor(ev())
    assert data[0] >> 5 == 5
    assert data[0] & 0x1F == 9  # nine fields


def test_op_id_validation():
    with pytest.raises(ValueError):
        ChangeEvent(op=OpKind.SET, key="k", val=b"v", ts=1, src="s", op_id=b"short")
    with pytest.raises(ValueError):
        ChangeEvent(op=OpKind.SET, key="k", val=b"v", ts=1, src="s",
                    prev=b"tooshort")


# ------------------------------------------------------------------ applier

@pytest.fixture
def store_and_applier():
    store: dict[bytes, bytes] = {}
    applier = LWWApplier(
        lambda k, v: store.__setitem__(k, v),
        lambda k: store.pop(k, None),
    )
    return store, applier


def test_apply_set_and_del(store_and_applier):
    store, a = store_and_applier
    assert a.apply(ev(ts=1))
    assert store == {b"k": b"v"}
    assert a.apply(ev(op=OpKind.DEL, val=None, ts=2))
    assert store == {}


def test_idempotency(store_and_applier):
    store, a = store_and_applier
    e = ev(ts=5)
    assert a.apply(e)
    assert not a.apply(e)  # duplicate op_id dropped
    assert a.skipped_dup == 1
    assert a.applied == 1


def test_lww_rejects_older(store_and_applier):
    store, a = store_and_applier
    a.apply(ev(ts=100, val=b"new"))
    assert not a.apply(ev(ts=50, val=b"old"))
    assert store[b"k"] == b"new"
    assert a.skipped_lww == 1


def test_lww_accepts_newer_and_equal_ordering(store_and_applier):
    store, a = store_and_applier
    a.apply(ev(ts=100, val=b"first"))
    assert a.apply(ev(ts=200, val=b"second"))
    assert store[b"k"] == b"second"


def test_tie_break_is_deterministic(store_and_applier):
    # Equal ts: larger op_id wins, regardless of arrival order
    # (change_event.rs:222-246 rule).
    store, a = store_and_applier
    lo = ev(ts=100, val=b"lo", op_id=b"\x01" * 16)
    hi = ev(ts=100, val=b"hi", op_id=b"\xfe" * 16)
    a.apply(lo)
    assert a.apply(hi)
    assert store[b"k"] == b"hi"

    store2: dict[bytes, bytes] = {}
    a2 = LWWApplier(lambda k, v: store2.__setitem__(k, v),
                    lambda k: store2.pop(k, None))
    a2.apply(hi)
    assert not a2.apply(lo)  # smaller op_id at equal ts is rejected
    assert store2[b"k"] == b"hi"


def test_post_op_semantics_incr_applies_as_set(store_and_applier):
    store, a = store_and_applier
    a.apply(ev(op=OpKind.INCR, val=b"7", ts=1))
    assert store[b"k"] == b"7"  # post-op result, not a re-executed increment
    a.apply(ev(op=OpKind.APPEND, val=b"7x", ts=2))
    assert store[b"k"] == b"7x"


def test_seen_set_is_bounded():
    store: dict[bytes, bytes] = {}
    a = LWWApplier(lambda k, v: store.__setitem__(k, v),
                   lambda k: store.pop(k, None), max_seen=10)
    for i in range(25):
        a.apply(ev(key=f"k{i}", ts=i + 1, op_id=i.to_bytes(16, "big")))
    assert len(a._seen) <= 11


def test_per_key_independence(store_and_applier):
    store, a = store_and_applier
    a.apply(ev(key="a", ts=100, val=b"1"))
    assert a.apply(ev(key="b", ts=50, val=b"2"))  # other key, older ts fine
    assert store == {b"a": b"1", b"b": b"2"}


def test_codecs_round_trip_non_utf8_key():
    """Keys/src that are surrogateescape-decoded raw bytes survive every
    codec (CBOR text items carry the raw bytes; JSON escapes surrogates)."""
    from merklekv_tpu.cluster.change_event import (
        decode_binary,
        decode_cbor,
        decode_json,
        encode_binary,
        encode_json,
    )

    raw = b"k\xff\x00\xfe"
    ev = ChangeEvent(
        op=OpKind.SET,
        key=raw.decode("utf-8", "surrogateescape"),
        val=b"v",
        ts=7,
        src="s",
    )
    for enc, dec in (
        (encode_cbor, decode_cbor),
        (encode_binary, decode_binary),
        (encode_json, decode_json),
    ):
        out = dec(enc(ev))
        assert out.key == ev.key
        assert out.key.encode("utf-8", "surrogateescape") == raw

"""ChangeEvent codecs + pure LWW applier (no transport).

Mirrors the reference's codec roundtrip tests and its LocalApplier fake
(change_event.rs:194-460): idempotency, LWW, deterministic ts tie-break —
tested as pure functions against a plain dict store.
"""

import pytest

from merklekv_tpu.cluster import (
    ChangeEvent,
    LWWApplier,
    OpKind,
    decode_any,
    decode_binary,
    decode_cbor,
    decode_json,
    encode_binary,
    encode_cbor,
    encode_json,
)
from merklekv_tpu.cluster.change_event import (
    coalesce_events,
    decode_events,
    encode_batch_cbor,
)


def ev(**kw) -> ChangeEvent:
    base = dict(op=OpKind.SET, key="k", val=b"v", ts=100, src="n1")
    base.update(kw)
    return ChangeEvent(**base)


# ------------------------------------------------------------------ codecs

@pytest.mark.parametrize(
    "enc,dec",
    [(encode_cbor, decode_cbor), (encode_binary, decode_binary),
     (encode_json, decode_json)],
)
def test_roundtrip_all_codecs(enc, dec):
    for e in [
        ev(),
        ev(op=OpKind.DEL, val=None),
        ev(op=OpKind.INCR, val=b"42"),
        ev(val=b"\x00\xff binary \t bytes"),
        ev(prev=b"\xab" * 32, ttl=3600),
        ev(key="unicode-ключ-☃", src="node-β"),
        ev(ts=2**63 + 5),  # > i64: u64 range must survive
    ]:
        assert dec(enc(e)) == e


def test_decode_any_tries_all():
    e = ev()
    assert decode_any(encode_cbor(e)) == e
    assert decode_any(encode_binary(e)) == e
    assert decode_any(encode_json(e)) == e
    with pytest.raises(ValueError):
        decode_any(b"\x00garbage not an event")
    with pytest.raises(ValueError):
        decode_any(b"")


def test_cbor_is_standard_subset():
    # A well-formed RFC 8949 map readable by any CBOR decoder: major 5 head.
    data = encode_cbor(ev())
    assert data[0] >> 5 == 5
    assert data[0] & 0x1F == 9  # nine fields


def test_op_id_validation():
    with pytest.raises(ValueError):
        ChangeEvent(op=OpKind.SET, key="k", val=b"v", ts=1, src="s", op_id=b"short")
    with pytest.raises(ValueError):
        ChangeEvent(op=OpKind.SET, key="k", val=b"v", ts=1, src="s",
                    prev=b"tooshort")


# ------------------------------------------------------------- batch frame

def test_batch_envelope_roundtrip():
    evs = [
        ev(key="a", val=b"1", ts=10),
        ev(op=OpKind.DEL, key="b", val=None, ts=20),
        ev(key=b"bin\xff\xfe".decode("utf-8", "surrogateescape"),
           val=b"\x00raw", ts=30),
        ev(op=OpKind.INCR, key="n", val=b"7", ts=40, prev=b"\xab" * 32,
           ttl=60),
    ]
    frame = encode_batch_cbor(evs, "node-α")
    out = decode_events(frame)
    # src rides the envelope once and is reinstated per event.
    assert out == [
        ChangeEvent(**{**e.__dict__, "src": "node-α"}) for e in evs
    ]
    # One frame is smaller than the per-event payloads it replaces.
    assert len(frame) < sum(len(encode_cbor(e)) for e in evs)


def test_decode_events_accepts_all_single_formats():
    e = ev(ts=77)
    for enc in (encode_cbor, encode_binary, encode_json):
        assert decode_events(enc(e)) == [e]
    with pytest.raises(ValueError):
        decode_events(b"\x00garbage")


def test_coalesce_events_last_write_per_key_wins():
    evs = [
        ev(key="a", val=b"1", ts=1, op_id=b"\x01" * 16),
        ev(key="b", val=b"2", ts=2, op_id=b"\x02" * 16),
        ev(key="a", val=b"3", ts=3, op_id=b"\x03" * 16),
        ev(op=OpKind.DEL, key="b", val=None, ts=4, op_id=b"\x04" * 16),
        ev(key="c", val=b"5", ts=5, op_id=b"\x05" * 16),
    ]
    kept, dropped = coalesce_events(evs)
    assert dropped == 2
    assert [(e.key, e.op) for e in kept] == [
        ("a", OpKind.SET), ("b", OpKind.DEL), ("c", OpKind.SET),
    ]
    # The survivors are the LAST event per key (post-op values make that
    # sufficient to reproduce final state).
    assert kept[0].val == b"3"


def test_batch_envelope_unknown_version_raises():
    frame = encode_batch_cbor([ev()], "s")
    bad = frame.replace(b"\x61v\x01", b"\x61v\x09", 1)  # v: 1 -> v: 9
    with pytest.raises(ValueError, match="envelope version"):
        decode_events(bad)


def test_batch_envelope_malformed_shapes_raise():
    # events not an array
    head = b"\xa3" + b"\x61v\x01" + b"\x63src\x61s" + b"\x66events\x01"
    with pytest.raises(ValueError):
        decode_events(head)
    # event entry not a map
    frame = (b"\xa3" + b"\x61v\x01" + b"\x63src\x61s" + b"\x66events"
             + b"\x81\x05")
    with pytest.raises(ValueError):
        decode_events(frame)
    # val decoded as a non-bytes CBOR item is rejected at the boundary
    # (letting it through would blow up inside the applier's FFI instead).
    bad_event = (
        b"\xa8"                       # map(8): event without src
        + b"\x61v\x01"                # v: 1
        + b"\x62op\x63set"            # op: "set"
        + b"\x63key\x61k"             # key: "k"
        + b"\x63val\x07"              # val: 7  (uint, INVALID)
        + b"\x62ts\x01"               # ts: 1
        + b"\x65op_id\x50" + b"\x00" * 16
        + b"\x64prev\xf6"
        + b"\x63ttl\xf6"
    )
    env = (b"\xa3" + b"\x61v\x01" + b"\x63src\x61s" + b"\x66events"
           + b"\x81" + bad_event)
    with pytest.raises(ValueError, match="val must be bytes"):
        decode_events(env)


def test_batch_envelope_truncation_fuzz_never_crashes():
    frame = encode_batch_cbor(
        [ev(key=f"k{i}", val=b"v%d" % i, ts=i + 1) for i in range(5)], "s"
    )
    for cut in range(len(frame)):
        with pytest.raises(ValueError):
            decode_events(frame[:cut])


def test_batch_envelope_byte_flip_fuzz_never_crashes():
    import random

    frame = encode_batch_cbor(
        [ev(key=f"k{i}", val=b"v%d" % i, ts=i + 1) for i in range(4)], "s"
    )
    rng = random.Random(1234)
    for _ in range(500):
        buf = bytearray(frame)
        for _ in range(rng.randint(1, 3)):
            buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
        try:
            out = decode_events(bytes(buf))
        except ValueError:
            continue  # counted-and-dropped is the contract
        # A surviving decode must be a well-formed event list.
        assert isinstance(out, list)
        for e in out:
            assert isinstance(e, ChangeEvent)
            assert e.val is None or isinstance(e.val, bytes)


# ------------------------------------------------------------------ applier

@pytest.fixture
def store_and_applier():
    store: dict[bytes, bytes] = {}
    applier = LWWApplier(
        lambda k, v: store.__setitem__(k, v),
        lambda k: store.pop(k, None),
    )
    return store, applier


def test_apply_set_and_del(store_and_applier):
    store, a = store_and_applier
    assert a.apply(ev(ts=1))
    assert store == {b"k": b"v"}
    assert a.apply(ev(op=OpKind.DEL, val=None, ts=2))
    assert store == {}


def test_idempotency(store_and_applier):
    store, a = store_and_applier
    e = ev(ts=5)
    assert a.apply(e)
    assert not a.apply(e)  # duplicate op_id dropped
    assert a.skipped_dup == 1
    assert a.applied == 1


def test_lww_rejects_older(store_and_applier):
    store, a = store_and_applier
    a.apply(ev(ts=100, val=b"new"))
    assert not a.apply(ev(ts=50, val=b"old"))
    assert store[b"k"] == b"new"
    assert a.skipped_lww == 1


def test_lww_accepts_newer_and_equal_ordering(store_and_applier):
    store, a = store_and_applier
    a.apply(ev(ts=100, val=b"first"))
    assert a.apply(ev(ts=200, val=b"second"))
    assert store[b"k"] == b"second"


def test_tie_break_is_deterministic(store_and_applier):
    # Equal ts: larger op_id wins, regardless of arrival order
    # (change_event.rs:222-246 rule).
    store, a = store_and_applier
    lo = ev(ts=100, val=b"lo", op_id=b"\x01" * 16)
    hi = ev(ts=100, val=b"hi", op_id=b"\xfe" * 16)
    a.apply(lo)
    assert a.apply(hi)
    assert store[b"k"] == b"hi"

    store2: dict[bytes, bytes] = {}
    a2 = LWWApplier(lambda k, v: store2.__setitem__(k, v),
                    lambda k: store2.pop(k, None))
    a2.apply(hi)
    assert not a2.apply(lo)  # smaller op_id at equal ts is rejected
    assert store2[b"k"] == b"hi"


def test_post_op_semantics_incr_applies_as_set(store_and_applier):
    store, a = store_and_applier
    a.apply(ev(op=OpKind.INCR, val=b"7", ts=1))
    assert store[b"k"] == b"7"  # post-op result, not a re-executed increment
    a.apply(ev(op=OpKind.APPEND, val=b"7x", ts=2))
    assert store[b"k"] == b"7x"


def test_seen_set_is_bounded():
    store: dict[bytes, bytes] = {}
    a = LWWApplier(lambda k, v: store.__setitem__(k, v),
                   lambda k: store.pop(k, None), max_seen=10)
    for i in range(25):
        a.apply(ev(key=f"k{i}", ts=i + 1, op_id=i.to_bytes(16, "big")))
    assert len(a._seen) <= 11


def test_per_key_independence(store_and_applier):
    store, a = store_and_applier
    a.apply(ev(key="a", ts=100, val=b"1"))
    assert a.apply(ev(key="b", ts=50, val=b"2"))  # other key, older ts fine
    assert store == {b"a": b"1", b"b": b"2"}


def test_apply_batch_fallback_matches_per_event(store_and_applier):
    """Without an engine batch fn, apply_batch is exactly the per-event
    path: same applied set, same counters."""
    store, a = store_and_applier
    evs = [
        ev(key="x", val=b"1", ts=10, op_id=b"\x0a" * 16),
        ev(key="x", val=b"0", ts=5, op_id=b"\x0b" * 16),   # stale
        ev(key="y", val=b"2", ts=20, op_id=b"\x0c" * 16),
        ev(key="y", val=b"2", ts=20, op_id=b"\x0c" * 16),  # dup op_id
    ]
    applied = a.apply_batch(evs)
    assert [e.key for e in applied] == ["x", "y"]
    assert store == {b"x": b"1", b"y": b"2"}
    assert a.skipped_dup == 1 and a.skipped_lww == 1


def test_apply_batch_engine_backed_one_ffi_crossing():
    """With the native engine's batch fn wired, a frame's surviving ops
    cross the FFI once, per-op flags drive the counters, and the outcome
    matches the per-event conditional verbs."""
    from merklekv_tpu.native_bindings import NativeEngine

    eng = NativeEngine("mem")
    calls = []

    def batch_fn(ops):
        calls.append(len(ops))
        return eng.apply_batch(ops)

    try:
        a = LWWApplier(
            eng.set,
            lambda k: eng.delete(k),
            set_ts_fn=lambda k, v, t: eng.set_if_newer(k, v, t),
            del_ts_fn=lambda k, t: eng.delete_if_newer(k, t),
            apply_batch_fn=batch_fn,
        )
        evs = [
            ev(key="a", val=b"1", ts=100, op_id=b"\x01" * 16),
            ev(op=OpKind.DEL, key="b", val=None, ts=200, op_id=b"\x02" * 16),
            ev(key="a", val=b"0", ts=50, op_id=b"\x03" * 16),  # mem-floor stale
            ev(key="a", val=b"1", ts=100, op_id=b"\x01" * 16),  # dup
        ]
        applied = a.apply_batch(evs)
        # ONE engine crossing: the intra-frame duplicate is filtered here;
        # the stale op rides along and the ENGINE's flag rejects it.
        assert calls == [3]
        assert [e.op_id for e in applied] == [b"\x01" * 16, b"\x02" * 16]
        assert eng.get(b"a") == b"1"
        assert eng.tombstone_ts(b"b") == 200
        assert a.applied == 2 and a.skipped_dup == 1 and a.skipped_lww == 1
        # A second frame with an ENGINE-stale op (ts below the installed
        # value, applier restarted so in-memory floor is empty) is rejected
        # by the engine flag, not silently applied.
        a2 = LWWApplier(
            eng.set, lambda k: eng.delete(k), apply_batch_fn=eng.apply_batch
        )
        out = a2.apply_batch([ev(key="a", val=b"old", ts=1,
                                 op_id=b"\x04" * 16)])
        assert out == [] and a2.skipped_lww == 1
        assert eng.get(b"a") == b"1"
    finally:
        eng.close()


def test_codecs_round_trip_non_utf8_key():
    """Keys/src that are surrogateescape-decoded raw bytes survive every
    codec (CBOR text items carry the raw bytes; JSON escapes surrogates)."""
    from merklekv_tpu.cluster.change_event import (
        decode_binary,
        decode_cbor,
        decode_json,
        encode_binary,
        encode_json,
    )

    raw = b"k\xff\x00\xfe"
    ev = ChangeEvent(
        op=OpKind.SET,
        key=raw.decode("utf-8", "surrogateescape"),
        val=b"v",
        ts=7,
        src="s",
    )
    for enc, dec in (
        (encode_cbor, decode_cbor),
        (encode_binary, decode_binary),
        (encode_json, decode_json),
    ):
        out = dec(enc(ev))
        assert out.key == ev.key
        assert out.key.encode("utf-8", "surrogateescape") == raw

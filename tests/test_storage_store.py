"""DurableStore: recovery, verification modes, compaction, event mapping.

In-process crash simulation: write with ``snapshot_on_shutdown=False`` (the
WAL stays the only recovery source), then tamper with the files the way a
crash/bit-rot would before recovering into a fresh engine. Real SIGKILL
crashes are covered process-level in tests/test_storage_chaos.py.
"""

import os
import time

import pytest

from merklekv_tpu.config import StorageConfig
from merklekv_tpu.native_bindings import (
    OP_DEL,
    OP_INCR,
    OP_SET,
    OP_TRUNCATE,
    ChangeEventRaw,
    NativeEngine,
)
from merklekv_tpu.storage import (
    DurableStore,
    RecoveryError,
    StorageLockedError,
)
from merklekv_tpu.storage import snapshot as snapmod
from merklekv_tpu.storage import wal as walmod
from merklekv_tpu.storage.walcheck import check_dir, replay_root_hex
from merklekv_tpu.testing.faults import corrupt_file, truncate_file
from merklekv_tpu.utils.tracing import get_metrics


def _cfg(**kw):
    kw.setdefault("enabled", True)
    kw.setdefault("fsync", "always")
    kw.setdefault("merkle_engine", "cpu")
    kw.setdefault("snapshot_on_shutdown", False)
    return StorageConfig(**kw)


@pytest.fixture
def engine():
    eng = NativeEngine("mem")
    yield eng
    eng.close()


def _fill(eng, store, n, base_ts=None):
    ts0 = base_ts if base_ts is not None else time.time_ns()
    for i in range(n):
        k, v = b"k%04d" % i, b"v-%d" % i
        eng.set_with_ts(k, v, ts0 + i)
        store.record_set(k, v, ts0 + i)
    return ts0


def test_recover_roundtrip_with_tombstones(tmp_path, engine):
    d = str(tmp_path / "node")
    st = DurableStore(engine, _cfg(), d)
    st.recover()
    ts0 = _fill(engine, st, 40)
    engine.delete_with_ts(b"k0007", ts0 + 100)
    st.record_delete(b"k0007", ts0 + 100)
    expect_root = engine.merkle_root().hex()
    st.stop()

    eng2 = NativeEngine("mem")
    try:
        st2 = DurableStore(eng2, _cfg(), d)
        rep = st2.recover()
        assert rep.replayed == 41
        assert eng2.merkle_root().hex() == expect_root
        assert eng2.get(b"k0007") is None
        # The tombstone survived with its LWW ordering: an older write
        # cannot resurrect the key after recovery.
        assert not eng2.set_if_newer(b"k0007", b"stale", ts0 + 50)
        assert eng2.set_if_newer(b"k0007", b"fresh", ts0 + 200)
        st2.stop()
    finally:
        eng2.close()


def test_snapshot_plus_wal_tail_is_idempotent(tmp_path, engine):
    """Records living in BOTH the snapshot and the WAL tail replay as
    no-ops — recovery applies LWW verbs, not blind inserts."""
    d = str(tmp_path / "node")
    st = DurableStore(engine, _cfg(), d)
    st.recover()
    _fill(engine, st, 30)
    st.snapshot_now()
    # More writes after the snapshot (land in the fresh segment).
    ts = time.time_ns() + 10_000
    engine.set_with_ts(b"post", b"snap", ts)
    st.record_set(b"post", b"snap", ts)
    expect_root = engine.merkle_root().hex()
    st.stop()

    eng2 = NativeEngine("mem")
    try:
        st2 = DurableStore(eng2, _cfg(), d)
        rep = st2.recover()
        assert rep.snapshot_items == 30
        assert eng2.merkle_root().hex() == expect_root
        assert eng2.dbsize() == 31
        st2.stop()
    finally:
        eng2.close()


def test_torn_tail_recovery_stops_at_last_whole_record(tmp_path, engine):
    d = str(tmp_path / "node")
    st = DurableStore(engine, _cfg(), d)
    st.recover()
    _fill(engine, st, 10)
    st.stop()
    seg = walmod.list_segments(d)[-1][1]
    truncate_file(seg, os.path.getsize(seg) - 5)  # tear the final frame

    eng2 = NativeEngine("mem")
    try:
        st2 = DurableStore(eng2, _cfg(), d)
        rep = st2.recover()
        assert rep.torn_tail
        assert rep.replayed == 9
        assert eng2.get(b"k0008") == b"v-8"
        assert eng2.get(b"k0009") is None
        # The reopened writer cut the tear: appends extend a clean log.
        ts = time.time_ns()
        st2.record_set(b"new", b"write", ts)
        st2.stop()
    finally:
        eng2.close()
    scan = walmod.scan_segment(seg)
    assert scan.clean
    assert scan.records[-1].key == b"new"


def test_strict_mode_refuses_on_root_mismatch(tmp_path, engine):
    d = str(tmp_path / "node")
    st = DurableStore(engine, _cfg(), d)
    st.recover()
    _fill(engine, st, 20)
    st.snapshot_now()
    st.stop()
    # Tamper with the stamp itself: rewrite the snapshot with a bogus root
    # (content + CRC stay valid, so only root verification can catch it).
    seq, path = snapmod.list_snapshots(d)[-1]
    snap = snapmod.read_snapshot(path)
    os.unlink(path)
    snapmod.write_snapshot(
        d, seq, snap.items, snap.tombstones, snap.wal_seq, "ab" * 32
    )

    eng2 = NativeEngine("mem")
    try:
        with pytest.raises(RecoveryError, match="walcheck"):
            DurableStore(eng2, _cfg(verify="strict"), d).recover()
    finally:
        eng2.close()


def test_repair_mode_falls_back_to_older_snapshot(tmp_path, engine):
    d = str(tmp_path / "node")
    st = DurableStore(engine, _cfg(snapshots_retained=2), d)
    st.recover()
    _fill(engine, st, 20)
    st.snapshot_now()  # snapshot 1: 20 items
    ts = time.time_ns() + 5_000
    engine.set_with_ts(b"later", b"write", ts)
    st.record_set(b"later", b"write", ts)
    expect_root = engine.merkle_root().hex()
    st.snapshot_now()  # snapshot 2: 21 items
    st.stop()
    m0 = get_metrics().snapshot()["counters"].get(
        "storage.recovery_root_mismatch", 0
    )
    corrupt_file(snapmod.list_snapshots(d)[-1][1], 60)  # kill the newest

    eng2 = NativeEngine("mem")
    try:
        st2 = DurableStore(eng2, _cfg(), d)
        rep = st2.recover()
        assert rep.snapshots_rejected
        assert rep.snapshot_items == 20  # older snapshot carried the load
        # The WAL tail behind the older snapshot replays the rest.
        assert eng2.get(b"later") == b"write"
        assert eng2.merkle_root().hex() == expect_root
        st2.stop()
    finally:
        eng2.close()
    after = get_metrics().snapshot()["counters"]
    assert after.get("storage.recovery_root_mismatch", 0) > m0


def test_interior_corruption_requests_reanchor_snapshot(tmp_path, engine):
    """Repair-mode recovery past interior WAL corruption must request a
    prompt snapshot: otherwise every future recovery replays up to the same
    bad segment and skips everything after it — including all
    post-recovery writes — until the byte trigger fires."""
    d = str(tmp_path / "node")
    st = DurableStore(engine, _cfg(segment_bytes=512), d)
    st.recover()
    _fill(engine, st, 40)  # spans several 512-byte segments
    st.stop()
    segs = walmod.list_segments(d)
    assert len(segs) >= 3
    # Interior corruption in the SECOND segment (not the tail): segment 0
    # replays fully, everything from the bad byte onward is skipped.
    corrupt_file(segs[1][1], 40)

    eng2 = NativeEngine("mem")
    try:
        st2 = DurableStore(eng2, _cfg(), d)
        rep = st2.recover()
        assert rep.corruption is not None
        assert rep.replayed > 0  # the clean prefix landed
        assert st2._snapshot_requested  # ticker will re-anchor promptly
        st2.snapshot_now()  # what the ticker does
        post_root = eng2.merkle_root().hex()
        st2.stop()
    finally:
        eng2.close()

    # The re-anchored state survives the NEXT recovery bit-exactly (the
    # bad segment no longer gates replay).
    eng3 = NativeEngine("mem")
    try:
        st3 = DurableStore(eng3, _cfg(), d)
        rep3 = st3.recover()
        assert rep3.corruption is None
        assert eng3.merkle_root().hex() == post_root
        st3.stop()
    finally:
        eng3.close()


def test_lock_rejects_second_owner(tmp_path, engine):
    d = str(tmp_path / "node")
    st = DurableStore(engine, _cfg(), d)
    st.recover()
    eng2 = NativeEngine("mem")
    try:
        with pytest.raises(StorageLockedError):
            DurableStore(eng2, _cfg(), d)
    finally:
        eng2.close()
    st.stop()
    # Released on stop: a successor may take the directory.
    eng3 = NativeEngine("mem")
    try:
        st3 = DurableStore(eng3, _cfg(), d)
        st3.recover()
        st3.stop()
    finally:
        eng3.close()


def test_record_raw_event_mapping(tmp_path, engine):
    """Drained native events map onto WAL records: value-carrying ops
    journal the POST-op value as a timestamped SET, deletes journal the
    tombstone ts, TRUNCATE journals the wipe."""
    d = str(tmp_path / "node")
    st = DurableStore(engine, _cfg(), d)
    st.recover()
    ts = time.time_ns()
    raws = [
        ChangeEventRaw(OP_SET, True, ts + 1, 1, b"a", b"1"),
        ChangeEventRaw(OP_INCR, True, ts + 2, 2, b"ctr", b"5"),
        ChangeEventRaw(OP_DEL, False, ts + 3, 3, b"a", b""),
        ChangeEventRaw(OP_TRUNCATE, False, ts + 4, 4, b"", b""),
        ChangeEventRaw(OP_SET, True, ts + 5, 5, b"b", b"2"),
    ]
    st.record_raw(raws)
    st.stop()
    scan = walmod.scan_segment(walmod.list_segments(d)[0][1])
    assert [r.op for r in scan.records] == [
        walmod.OP_SET,
        walmod.OP_SET,
        walmod.OP_DEL,
        walmod.OP_TRUNCATE,
        walmod.OP_SET,
    ]
    eng2 = NativeEngine("mem")
    try:
        DurableStore(eng2, _cfg(), d).recover()
        # Everything before the TRUNCATE is gone; only b survives.
        assert eng2.scan() == [b"b"]
    finally:
        eng2.close()


def test_compaction_retention(tmp_path, engine):
    d = str(tmp_path / "node")
    st = DurableStore(
        engine, _cfg(snapshots_retained=2, segment_bytes=512), d
    )
    st.recover()
    for round_ in range(3):
        _fill(engine, st, 40, base_ts=time.time_ns())
        st.compact()
    snaps = snapmod.list_snapshots(d)
    assert len(snaps) == 2  # retention pruned the oldest
    oldest_needed = min(
        snapmod.read_snapshot(p).wal_seq for _, p in snaps
    )
    assert all(s >= oldest_needed for s, _ in walmod.list_segments(d))
    expect_root = engine.merkle_root().hex()
    st.stop()
    eng2 = NativeEngine("mem")
    try:
        st2 = DurableStore(eng2, _cfg(), d)
        st2.recover()
        assert eng2.merkle_root().hex() == expect_root
        st2.stop()
    finally:
        eng2.close()


def test_metrics_counters(tmp_path, engine):
    before = get_metrics().snapshot()["counters"]
    d = str(tmp_path / "node")
    st = DurableStore(engine, _cfg(), d)
    st.recover()
    _fill(engine, st, 15)
    st.snapshot_now()
    ts = time.time_ns() + 1_000
    engine.set_with_ts(b"tail", b"record", ts)
    st.record_set(b"tail", b"record", ts)  # replays from the WAL tail
    st.stop()
    eng2 = NativeEngine("mem")
    try:
        DurableStore(eng2, _cfg(), d).recover()
    finally:
        eng2.close()
    after = get_metrics().snapshot()
    c = after["counters"]

    def grew(name, by=1):
        return c.get(name, 0) >= before.get(name, 0) + by

    assert grew("storage.wal_appends", 15)
    assert grew("storage.wal_fsyncs", 1)
    assert grew("storage.snapshots", 1)
    assert grew("storage.recovery_replayed", 1)
    assert grew("storage.recoveries", 2)
    assert "storage.snapshot" in after["spans"]  # snapshot_seconds source
    assert "storage.recovery" in after["spans"]


def test_walcheck_clean_dir_and_replay_root(tmp_path, engine):
    d = str(tmp_path / "node")
    st = DurableStore(engine, _cfg(), d)
    st.recover()
    ts0 = _fill(engine, st, 25)
    engine.delete_with_ts(b"k0003", ts0 + 90)
    st.record_delete(b"k0003", ts0 + 90)
    st.snapshot_now()
    expect_root = engine.merkle_root().hex()
    st.stop()

    report = check_dir(d)
    assert not report["errors"] and not report["warnings"]
    assert report["replay_root"] == expect_root
    assert report["live_keys"] == 24
    assert replay_root_hex(d) == expect_root


def test_walcheck_flags_torn_tail_as_warning_and_compacts(tmp_path, engine):
    d = str(tmp_path / "node")
    st = DurableStore(engine, _cfg(), d)
    st.recover()
    _fill(engine, st, 12)
    st.stop()
    seg = walmod.list_segments(d)[-1][1]
    truncate_file(seg, os.path.getsize(seg) - 4)

    report = check_dir(d)
    assert not report["errors"]  # torn tail is recoverable, not fatal
    assert any("torn tail" in w for w in report["warnings"])
    assert report["live_keys"] == 11

    # Offline compaction rewrites to one verified snapshot + empty WAL.
    from merklekv_tpu.storage.walcheck import main as walcheck_main

    assert walcheck_main([d, "--compact"]) == 0
    assert len(snapmod.list_snapshots(d)) == 1
    assert walmod.list_segments(d) == []
    eng2 = NativeEngine("mem")
    try:
        st2 = DurableStore(eng2, _cfg(), d)
        rep = st2.recover()
        assert rep.snapshot_items == 11
        assert eng2.get(b"k0010") == b"v-10"
        st2.stop()
    finally:
        eng2.close()


def test_replication_writes_reach_the_wal(tmp_path):
    """With replication enabled the Replicator owns the event-queue drain;
    local writes must reach the WAL through its batch listener and REMOTE
    applies through the storage hook — both survive recovery."""
    import uuid

    from merklekv_tpu.client import MerkleKVClient
    from merklekv_tpu.cluster.node import ClusterNode
    from merklekv_tpu.cluster.transport import TcpBroker
    from merklekv_tpu.config import Config
    from merklekv_tpu.native_bindings import NativeServer

    broker = TcpBroker()
    topic = f"st-{uuid.uuid4().hex[:8]}"
    nodes = []
    try:
        for i in (1, 2):
            eng = NativeEngine("mem")
            srv = NativeServer(eng, "127.0.0.1", 0)
            srv.start()
            cfg = Config()
            cfg.replication.enabled = True
            cfg.replication.mqtt_broker = broker.host
            cfg.replication.mqtt_port = broker.port
            cfg.replication.topic_prefix = topic
            cfg.replication.client_id = f"n{i}"
            cfg.anti_entropy.engine = "cpu"  # no device mirror in this test
            store = DurableStore(eng, _cfg(), str(tmp_path / f"n{i}"))
            store.recover()
            node = ClusterNode(cfg, eng, srv, storage=store)
            node.start()
            client = MerkleKVClient("127.0.0.1", srv.port).connect()
            nodes.append((eng, srv, store, node, client))

        c1, c2 = nodes[0][4], nodes[1][4]
        c1.set("local-write", "from-n1")
        deadline = time.time() + 5
        while time.time() < deadline and c2.get("local-write") != "from-n1":
            time.sleep(0.01)
        assert c2.get("local-write") == "from-n1"
        roots = [eng.merkle_root().hex() for eng, *_ in nodes]
        assert roots[0] == roots[1]
    finally:
        dirs = []
        for eng, srv, store, node, client in nodes:
            client.close()
            node.stop()
            store.stop()
            dirs.append(store.directory)
            srv.close()
            eng.close()
        broker.close()

    # n1 journaled its local write (batch listener), n2 its remote apply
    # (storage hook inside the Replicator) — both recover to the same root.
    for d in dirs:
        eng = NativeEngine("mem")
        try:
            st = DurableStore(eng, _cfg(), d)
            st.recover()
            assert eng.get(b"local-write") == b"from-n1"
            assert eng.merkle_root().hex() == roots[0]
            st.stop()
        finally:
            eng.close()


def test_walcheck_flags_root_mismatch_as_error(tmp_path, engine):
    d = str(tmp_path / "node")
    st = DurableStore(engine, _cfg(), d)
    st.recover()
    _fill(engine, st, 10)
    st.snapshot_now()
    st.stop()
    seq, path = snapmod.list_snapshots(d)[-1]
    snap = snapmod.read_snapshot(path)
    os.unlink(path)
    snapmod.write_snapshot(
        d, seq, snap.items, snap.tombstones, snap.wal_seq, "cd" * 32
    )
    from merklekv_tpu.storage.walcheck import main as walcheck_main

    report = check_dir(d)
    assert any("root mismatch" in e for e in report["errors"])
    assert walcheck_main([d]) == 1

"""Convergence-lag SLO plane (obs/lag.py): envelope publish HWMs, the
per-peer lag gauges, residue clearing on anti-entropy convergence, and
the /healthz readiness transitions.

Acceptance (ISSUE 7): per-peer ``replication.lag_events`` returns to 0
after convergence, and ``/healthz`` readiness transitions lagging→live.
"""

from __future__ import annotations

import json
import time
import urllib.request
import uuid

import pytest

from merklekv_tpu.client import MerkleKVClient
from merklekv_tpu.cluster.change_event import (
    ChangeEvent,
    OpKind,
    decode_events,
    decode_events_meta,
    encode_batch_cbor,
    encode_cbor,
)
from merklekv_tpu.cluster.node import ClusterNode
from merklekv_tpu.cluster.transport import TcpBroker
from merklekv_tpu.config import Config
from merklekv_tpu.native_bindings import NativeEngine, NativeServer
from merklekv_tpu.obs.lag import ConvergenceTracker


def _ev(key: str, src: str = "peer-a") -> ChangeEvent:
    return ChangeEvent.new(OpKind.SET, key, b"v", src)


# ---------------------------------------------------------- envelope HWM

def test_envelope_carries_hwm_and_trace():
    events = [_ev("a"), _ev("b")]
    payload = encode_batch_cbor(
        events, "peer-a", hwm_seq=17, hwm_ts=123456789,
        trace="tc=" + "1" * 16 + "-" + "2" * 16 + "-01",
    )
    out, meta = decode_events_meta(payload)
    assert [e.key for e in out] == ["a", "b"]
    assert meta["src"] == "peer-a"
    assert meta["hseq"] == 17
    assert meta["hts"] == 123456789
    assert meta["tc"].startswith("tc=")
    # Plain decode_events still works on the stamped envelope.
    assert len(decode_events(payload)) == 2


def test_envelope_without_hwm_stays_compatible():
    payload = encode_batch_cbor([_ev("a")], "peer-a")
    out, meta = decode_events_meta(payload)
    assert len(out) == 1
    assert meta == {"src": "peer-a"}


def test_legacy_single_event_meta():
    ev = _ev("solo", src="old-node")
    out, meta = decode_events_meta(encode_cbor(ev))
    assert [e.key for e in out] == ["solo"]
    assert meta == {"src": "old-node"}


# -------------------------------------------------------------- tracker

def test_tracker_baseline_then_catchup():
    t = ConvergenceTracker()
    # First sight mid-stream: baselined, not back-charged.
    t.on_frame("a", 10, hseq=1000, hts_ns=time.time_ns())
    assert t.lag_events()["a"] == 10
    t.on_applied("a", 10, hts_ns=time.time_ns())
    assert t.lag_events()["a"] == 0
    assert t.readiness() == "live"


def test_tracker_drop_residue_cleared_by_convergence():
    t = ConvergenceTracker()
    now = time.time_ns()
    t.on_frame("a", 5, hseq=5, hts_ns=now)
    t.on_applied("a", 5, hts_ns=now)
    # A dropped frame: seen via the NEXT frame's HWM jump.
    t.on_frame("a", 3, hseq=13, hts_ns=now)  # 5 events never arrived
    t.on_applied("a", 3, hts_ns=now)
    assert t.lag_events()["a"] == 5
    assert t.readiness() == "lagging"
    # Anti-entropy converged (root comparison): residue is repaired data.
    t.on_converged()
    assert t.lag_events()["a"] == 0
    assert t.readiness() == "live"


def test_tracker_diverged_after_persistent_residue():
    t = ConvergenceTracker(diverged_after_s=0.05)
    t.on_frame("a", 2, hseq=10, hts_ns=time.time_ns())
    t.on_applied("a", 2, hts_ns=time.time_ns())
    t.on_frame("a", 1, hseq=20, hts_ns=time.time_ns())  # gap of 9
    t.on_applied("a", 1, hts_ns=time.time_ns())
    assert t.readiness() == "lagging"
    time.sleep(0.08)
    assert t.readiness() == "diverged"
    t.on_converged()
    assert t.readiness() == "live"


def test_tracker_slow_apply_reads_lagging():
    t = ConvergenceTracker(lag_ms_threshold=1.0)
    old = time.time_ns() - int(50e6)  # published 50 ms ago
    t.on_frame("a", 1, hseq=1, hts_ns=old)
    t.on_applied("a", 1, hts_ns=old)
    assert t.lag_events()["a"] == 0
    assert t.lag_ms()["a"] >= 40.0
    assert t.readiness() == "lagging"


def test_tracker_ignores_hwmless_frames():
    t = ConvergenceTracker()
    t.on_frame("old", 4)  # legacy publisher: no HWM
    t.on_applied("old", 4)
    assert t.lag_events().get("old", 0) == 0
    assert t.readiness() == "live"


# -------------------------------------------------- cluster integration

@pytest.fixture
def cluster():
    broker = TcpBroker()
    topic = f"lag-{uuid.uuid4().hex[:8]}"
    made = []
    for name in ("lag-a", "lag-b"):
        eng = NativeEngine("mem")
        srv = NativeServer(eng, "127.0.0.1", 0)
        srv.start()
        cfg = Config()
        cfg.replication.enabled = True
        cfg.replication.mqtt_broker = broker.host
        cfg.replication.mqtt_port = broker.port
        cfg.replication.topic_prefix = topic
        cfg.replication.client_id = name
        cfg.anti_entropy.engine = "cpu"
        cfg.observability.http_port = -1
        # Readiness in this test must hinge on lag RESIDUE alone: the
        # deliberate apply hold below inflates publish->apply delay, which
        # must not keep readiness at "lagging" after release on a slow CI.
        cfg.observability.lag_ms_threshold = 120_000.0
        node = ClusterNode(cfg, eng, srv)
        node.start()
        made.append((eng, srv, node))
    yield broker, made
    for eng, srv, node in reversed(made):
        node.stop()
        srv.close()
        eng.close()
    broker.close()


def _healthz(node) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{node.metrics_port}/healthz", timeout=5
    ) as r:
        return json.loads(r.read())


def _wait(pred, timeout=20.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def test_lag_returns_to_zero_and_healthz_transitions(cluster):
    """Tier-1 acceptance: held frames read as per-peer lag_events > 0 and
    /healthz "lagging"; releasing applies drains the lag to 0 and
    readiness transitions back to "live"."""
    broker, made = cluster
    (eng_a, srv_a, node_a), (eng_b, srv_b, node_b) = made

    node_b.replicator.hold_applies()
    with MerkleKVClient("127.0.0.1", srv_a.port) as c:
        for i in range(40):
            c.set(f"lg:{i:04d}", f"v{i}")
    assert _wait(
        lambda: node_b.lag_tracker.lag_events().get("lag-a", 0) >= 40
    ), node_b.lag_tracker.lag_events()
    assert node_b.lag_tracker.readiness() == "lagging"
    hz = _healthz(node_b)
    assert hz["readiness"] == "lagging"
    assert hz["lag_events"] >= 40

    node_b.replicator.release_applies()
    assert _wait(
        lambda: node_b.lag_tracker.lag_events().get("lag-a", 1) == 0
    ), node_b.lag_tracker.lag_events()
    assert _wait(lambda: node_b.lag_tracker.readiness() == "live")
    assert _healthz(node_b)["readiness"] == "live"
    # The applied writes actually landed.
    assert _wait(lambda: eng_b.dbsize() == 40)

    # METRICS wire carries the same numbers for wire-only consumers (top);
    # the block's contract is integer text, so readiness rides as a code.
    with MerkleKVClient("127.0.0.1", srv_b.port) as c:
        m = c.metrics()
    assert m.get("replication.lag_events.lag-a") == "0"
    assert "replication.lag_ms.lag-a" in m
    assert m.get("readiness_code") == "2"
    assert all(v.lstrip("-").isdigit() for v in m.values()), m


def test_convergence_histogram_observed(cluster):
    broker, made = cluster
    (eng_a, srv_a, node_a), (eng_b, srv_b, node_b) = made
    from merklekv_tpu.utils.tracing import get_metrics

    before = get_metrics().histogram("replication.convergence").snapshot()[
        "count"
    ]
    with MerkleKVClient("127.0.0.1", srv_a.port) as c:
        for i in range(10):
            c.set(f"cv:{i:03d}", "x")
    assert _wait(lambda: eng_b.dbsize() >= 10)
    assert _wait(
        lambda: get_metrics()
        .histogram("replication.convergence")
        .snapshot()["count"]
        > before
    )


def test_lag_gauges_exported(cluster):
    broker, made = cluster
    (eng_a, srv_a, node_a), (eng_b, srv_b, node_b) = made
    with MerkleKVClient("127.0.0.1", srv_a.port) as c:
        c.set("gx", "1")
    assert _wait(lambda: eng_b.dbsize() >= 1)
    with urllib.request.urlopen(
        f"http://127.0.0.1:{node_b.metrics_port}/metrics", timeout=5
    ) as r:
        page = r.read().decode()
    assert 'mkv_replication_lag_events{src="lag-a"}' in page
    assert 'mkv_replication_lag_ms{src="lag-a"}' in page
    assert "mkv_node_readiness" in page
    assert "mkv_replication_convergence_seconds_bucket" in page


def test_only_full_clean_pass_clears_residue():
    """Review hardening: a pairwise pass that could not cover every
    configured peer (one down) must NOT clear dropped-frame residue —
    converging with peer A proves nothing about a partitioned peer B's
    events; a later full clean pass does clear it."""
    import socket

    from merklekv_tpu.cluster.retry import RetryPolicy
    from merklekv_tpu.cluster.sync import SyncManager

    tracker = ConvergenceTracker()
    now = time.time_ns()
    tracker.on_frame("b", 1, hseq=10, hts_ns=now)
    tracker.on_applied("b", 1, hts_ns=now)
    tracker.on_frame("b", 1, hseq=20, hts_ns=now)  # 9 events dropped
    tracker.on_applied("b", 1, hts_ns=now)
    assert tracker.lag_events()["b"] == 9

    eng_l = NativeEngine("mem")
    eng_r = NativeEngine("mem")
    srv = NativeServer(eng_r, "127.0.0.1", 0)
    srv.start()
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()  # nothing listening: instant ECONNREFUSED
    fast = RetryPolicy(first_delay=0.01, max_delay=0.02, jitter=0.0,
                       attempts=1, op_timeout=0.5, op_deadline=5.0)
    try:
        up_peer = f"127.0.0.1:{srv.port}"
        mgr = SyncManager(
            eng_l, device="cpu", retry=fast,
            on_cycle_converged=tracker.on_converged,
        )
        mgr.start_loop([up_peer, f"127.0.0.1:{dead_port}"], 0.05)
        time.sleep(0.8)
        mgr.stop()
        assert tracker.lag_events()["b"] == 9, "partial pass cleared residue"

        mgr2 = SyncManager(
            eng_l, device="cpu", retry=fast,
            on_cycle_converged=tracker.on_converged,
        )
        mgr2.start_loop([up_peer], 0.05)
        deadline = time.time() + 10
        while time.time() < deadline and tracker.lag_events()["b"] != 0:
            time.sleep(0.05)
        mgr2.stop()
        assert tracker.lag_events()["b"] == 0
    finally:
        srv.close()
        eng_l.close()
        eng_r.close()

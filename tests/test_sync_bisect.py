"""Subtree-bisection anti-entropy: the O(divergence·log n) wire-byte walk.

The reference *documents* a top-down hash-comparison walk
(/root/reference/README.md:310-372) but ships full snapshot transfer; our
hash-first mode still shipped the whole leaf-hash list (O(n·32B)) whenever
roots differed. The bisection walk (TREELEVEL descent + range-bounded
HASHPAGE repair) makes wire bytes scale with divergence·log n:

- walk parity: converged roots bit-identical across the CPU golden tree,
  the device-resident tree, the native host tree, and both peers;
- wire-byte accounting: 1 divergent key in a >= 1M-key keyspace syncs with
  a few KB on the wire (hash-first would ship ~32 MB of digests);
- fault tolerance: a stream killed mid-walk checkpoints (cursor, walk) into
  the SyncSession and the next cycle RESUMES the walk;
- degradation: peers without TREELEVEL, empty peers, and keyspace churn all
  fall back to the paged hash scan.
"""

from __future__ import annotations

import time

import pytest

from merklekv_tpu.client import MerkleKVClient, ProtocolError
from merklekv_tpu.cluster.retry import RetryPolicy
from merklekv_tpu.cluster.sync import SyncManager
from merklekv_tpu.native_bindings import NativeEngine, NativeServer


@pytest.fixture
def two_nodes():
    nodes = []
    for _ in range(2):
        eng = NativeEngine("mem")
        srv = NativeServer(eng, "127.0.0.1", 0)
        srv.start()
        nodes.append((eng, srv))
    yield nodes
    for eng, srv in nodes:
        srv.close()
        eng.close()


def fill(eng, items):
    for k, v in items.items():
        eng.set(k.encode(), v.encode())


# ------------------------------------------------------------ wire verbs


def test_treelevel_serves_reference_levels(two_nodes):
    """TREELEVEL rows are bit-identical to the CPU golden tree's levels
    (including the odd-promotion spine) and carry the live leaf count."""
    from merklekv_tpu.merkle.cpu import build_levels
    from merklekv_tpu.merkle.encoding import leaf_hash

    (_, _), (eng, srv) = two_nodes
    items = {f"tl{i:03d}": f"v{i}" for i in range(100)}
    fill(eng, items)
    gold = build_levels(
        [leaf_hash(k, v) for k, v in sorted(items.items())]
    )
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        # Zero-width probe: capability check + leaf count, no rows.
        rows, n = c.tree_level(0, 0, 0)
        assert rows == [] and n == 100
        for lvl, level_nodes in enumerate(gold):
            rows, n = c.tree_level(lvl, 0, 10**6)  # hi clamps to the level
            assert n == 100
            assert [i for i, _ in rows] == list(range(len(level_nodes)))
            assert [bytes.fromhex(h) for _, h in rows] == level_nodes
        # Past the top level: no rows, but the leaf count still answers.
        rows, n = c.tree_level(len(gold) + 3, 0, 10)
        assert rows == [] and n == 100
        # The served root equals HASH.
        rows, _ = c.tree_level(len(gold) - 1, 0, 1)
        assert rows[0][1] == c.hash()


def test_treelevel_requires_arguments(two_nodes):
    (_, _), (_, srv) = two_nodes
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        assert c._request("TREELEVEL").startswith("ERROR")
        assert c._request("TREELEVEL 0 5 2").startswith("ERROR")
        assert c._request("TREELEVEL -1 0 2").startswith("ERROR")


def test_hashpage_upto_bounds_the_page(two_nodes):
    """Range-bounded HASHPAGE: rows stop strictly below the bound, a short
    page means the RANGE (not the keyspace) is exhausted, and tombstones
    inside the range still ride along."""
    (_, _), (eng, srv) = two_nodes
    fill(eng, {f"hp{i:02d}": "v" for i in range(20)})
    eng.delete(b"hp07")
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        rows, done = c.leaf_hashes_page(100, "hp04", upto="hp09")
        assert [r[0] for r in rows] == ["hp05", "hp06", "hp07", "hp08"]
        assert rows[2][1] is None  # tombstone row in-range
        assert done  # range exhausted, keyspace is not
        # Unbounded continuation from the same cursor keeps going.
        rows, done = c.leaf_hashes_page(100, "hp09")
        assert [r[0] for r in rows] == [f"hp{i}" for i in range(10, 20)]
        # Degenerate bound is a parse error, not silent weirdness.
        with pytest.raises(ProtocolError, match="upto"):
            c.leaf_hashes_page(10, "hp09", upto="hp04")
        # Client refuses the inexpressible empty-cursor + bound form.
        with pytest.raises(ValueError):
            c.leaf_hashes_page(10, "", upto="hp04")


# ------------------------------------------------------------ the walk


def test_bisect_converges_and_roots_match_every_engine(two_nodes):
    """Walk parity: after a bisection sync both peers, the CPU golden tree,
    the device-resident tree, and the native host tree agree bit-exactly."""
    from merklekv_tpu.merkle.cpu import MerkleTree
    from merklekv_tpu.merkle.incremental import DeviceMerkleState

    (local_eng, _), (remote_eng, remote_srv) = two_nodes
    items = {f"bk{i:04d}": f"v{i}" for i in range(800)}
    fill(remote_eng, items)
    fill(local_eng, items)
    for i in range(0, 800, 97):
        local_eng.set(f"bk{i:04d}".encode(), b"stale")
    local_eng.set(b"bk-local-only", b"x")
    remote_eng.delete(b"bk0400")
    remote_eng.set(b"bk-remote-only", b"y")

    mgr = SyncManager(local_eng, device="cpu", mode="bisect")
    report = mgr.sync_once("127.0.0.1", remote_srv.port)

    assert report.mode == "bisect"
    assert report.rounds > 0 and report.nodes_compared > 0
    assert report.bytes_sent > 0 and report.bytes_received > 0
    assert local_eng.snapshot() == remote_eng.snapshot()

    native_root = local_eng.merkle_root()
    assert native_root == remote_eng.merkle_root()
    golden = MerkleTree.from_items(
        [
            (k.decode(), v)
            for k, v in local_eng.snapshot()
        ]
    )
    assert golden.root_hash() == native_root
    device = DeviceMerkleState.from_items(local_eng.snapshot())
    assert device.root_hash() == native_root


def test_bisect_one_divergent_key_in_1m_costs_kilobytes(two_nodes):
    """THE acceptance bar: 1 divergent key in a >= 1M-key keyspace syncs
    with a few KB on the wire. Hash-first ships the whole digest list
    (~32 MB of raw digests, ~70 MB as wire hex) whenever roots differ —
    the walk replaces that with O(log n) interior nodes + one bounded leaf
    page + one value.

    Deliberately tier-1 (the acceptance bar demands the >= 1M-key scale):
    measured ~28 s on the CI-class CPU — the bulk is the 2x1M engine fills
    and the one-time local/remote tree builds, well inside the tier-1
    budget."""
    (local_eng, _), (remote_eng, remote_srv) = two_nodes
    n = 1 << 20
    for i in range(n):
        k = b"u%07d" % i
        v = b"val-%d" % (i % 9973)
        local_eng.set(k, v)
        remote_eng.set(k, v)
    local_eng.set(b"u0524288", b"DIVERGED")  # 1 stale key in the middle

    mgr = SyncManager(local_eng, device="cpu", mode="bisect")
    report = mgr.sync_once("127.0.0.1", remote_srv.port)

    assert report.mode == "bisect"
    assert report.divergent == 1
    assert report.set_keys == 1 and report.values_fetched == 1
    wire = report.bytes_sent + report.bytes_received
    # "A few hundred KB" is the acceptance ceiling; the walk actually lands
    # near ~5 KB (log2(1M) TREELEVEL rounds + one 16-leaf page + 1 value).
    # Hash-first at this size ships >= 32 MB of digests.
    assert wire < 300_000, f"walk cost {wire} bytes"
    assert wire < (n * 32) // 100, "not even 1% of the raw digest list"

    # Converged roots are bit-identical: both peers' native trees and the
    # CPU golden spec (the device tree's parity at this scale is covered by
    # the jax golden suites; see test_bisect_converges_... for the
    # in-sync-path device check).
    from merklekv_tpu.merkle.cpu import build_levels
    from merklekv_tpu.merkle.encoding import leaf_hash

    native_root = local_eng.merkle_root()
    assert native_root == remote_eng.merkle_root()
    golden_root = build_levels(
        [leaf_hash(k, v) for k, v in local_eng.snapshot()]
    )[-1][0]
    assert golden_root == native_root

    # Observability: the cycle's transfer cost landed in the metrics.
    from merklekv_tpu.utils.tracing import get_metrics

    counters = get_metrics().snapshot()["counters"]
    assert counters.get("sync.bytes_sent", 0) > 0
    assert counters.get("sync.bytes_received", 0) > 0
    assert counters.get("sync.nodes_compared", 0) > 0
    assert counters.get("sync.rounds", 0) > 0


def test_auto_mode_selects_by_keyspace_size(two_nodes):
    """auto = paged below the threshold (fewer round trips), bisect at or
    above it; "page" pins the scan even on a big keyspace."""
    (local_eng, _), (remote_eng, remote_srv) = two_nodes
    items = {f"am{i:03d}": f"v{i}" for i in range(400)}
    fill(remote_eng, items)
    fill(local_eng, items)
    local_eng.set(b"am000", b"stale")

    r = SyncManager(local_eng, device="cpu").sync_once(
        "127.0.0.1", remote_srv.port
    )
    assert r.mode == "hash-paged"  # 400 < default threshold

    local_eng.set(b"am001", b"stale")
    r = SyncManager(
        local_eng, device="cpu", bisect_threshold=100
    ).sync_once("127.0.0.1", remote_srv.port)
    assert r.mode == "bisect"

    local_eng.set(b"am002", b"stale")
    r = SyncManager(
        local_eng, device="cpu", mode="page", bisect_threshold=100
    ).sync_once("127.0.0.1", remote_srv.port)
    assert r.mode == "hash-paged"
    assert local_eng.snapshot() == remote_eng.snapshot()


def test_bisect_falls_back_without_treelevel(two_nodes, monkeypatch):
    """A peer that answers ERROR to TREELEVEL (old binary) degrades to the
    paged scan in the same cycle — no wedging, still converges."""
    (local_eng, _), (remote_eng, remote_srv) = two_nodes
    items = {f"fb{i:03d}": f"v{i}" for i in range(300)}
    fill(remote_eng, items)
    fill(local_eng, items)
    local_eng.set(b"fb000", b"stale")

    def no_treelevel(self, level, lo, hi):
        raise ProtocolError("Unknown command: TREELEVEL")

    monkeypatch.setattr(MerkleKVClient, "tree_level", no_treelevel)
    mgr = SyncManager(local_eng, device="cpu", mode="bisect")
    report = mgr.sync_once("127.0.0.1", remote_srv.port)
    assert report.mode == "hash-paged"
    assert local_eng.snapshot() == remote_eng.snapshot()


def test_bisect_empty_remote_clears_local(two_nodes):
    (local_eng, _), (_, remote_srv) = two_nodes
    fill(local_eng, {f"er{i}": "v" for i in range(50)})
    mgr = SyncManager(local_eng, device="cpu", mode="bisect")
    report = mgr.sync_once("127.0.0.1", remote_srv.port)
    # Empty peer: the walk declines (nothing to bisect) and paging mirrors
    # the emptiness.
    assert report.mode == "hash-paged"
    assert local_eng.dbsize() == 0


# ------------------------------------------------ faults + resume


FAST = RetryPolicy(
    first_delay=0.01,
    max_delay=0.05,
    jitter=0.0,
    attempts=2,
    op_timeout=0.5,
    op_deadline=30.0,
)


def test_bisect_walk_resumes_from_checkpoint_under_kill(two_nodes):
    """A stream killed mid-walk checkpoints (cursor, walk=True) into the
    SyncSession; the next cycle resumes the WALK (not the paged scan) from
    the verified frontier and the pair converges."""
    from merklekv_tpu.testing.faults import FaultInjector

    (local_eng, _), (remote_eng, remote_srv) = two_nodes
    base = {f"fw{i:04d}": f"v{i}" for i in range(600)}
    fill(remote_eng, base)
    fill(local_eng, base)
    # Spread divergence so the repair stream is long enough to kill.
    for i in range(0, 600, 3):
        local_eng.set(f"fw{i:04d}".encode(), b"stale")

    inj = FaultInjector("127.0.0.1", remote_srv.port, seed=17)
    peer = f"{inj.host}:{inj.port}"
    degraded: list[tuple[str, str]] = []
    mgr = SyncManager(
        local_eng,
        device="cpu",
        mode="bisect",
        mget_batch=8,
        hash_page=32,
        retry=FAST,
        on_peer_degraded=lambda p, r: degraded.append((p, r)),
    )
    try:
        inj.kill_after_bytes(6000, direction="s2c")
        with pytest.raises(Exception):
            mgr.sync_once(inj.host, inj.port)
        sess = mgr.session_for(peer)
        assert sess is not None, "mid-walk death must checkpoint"
        assert sess.walk, "checkpoint must remember the walk mode"
        assert degraded, "mid-walk death must degrade the peer"

        inj.revive()
        resumed = False
        for _ in range(40):
            try:
                report = mgr.sync_once(inj.host, inj.port)
                resumed = resumed or report.resumed
            except Exception:
                continue
            if local_eng.merkle_root() == remote_eng.merkle_root():
                break
        assert resumed, "at least one cycle must resume the session"
        assert local_eng.merkle_root() == remote_eng.merkle_root()
        assert local_eng.snapshot() == remote_eng.snapshot()
    finally:
        inj.close()


def test_bisect_walk_converges_under_drop_and_truncate(two_nodes):
    """Chunk drops + truncation faults on the walk path: individual cycles
    may die, but checkpoint/resume keeps progress monotonic and the pair
    converges (the satellite chaos bar for the new transfer mode)."""
    from merklekv_tpu.testing.faults import FaultInjector

    (local_eng, _), (remote_eng, remote_srv) = two_nodes
    base = {f"dt{i:04d}": f"v{i}" for i in range(500)}
    fill(remote_eng, base)
    fill(local_eng, {f"dt{i:04d}": "stale" for i in range(250)})

    inj = FaultInjector("127.0.0.1", remote_srv.port, seed=23)
    mgr = SyncManager(
        local_eng, device="cpu", mode="bisect",
        mget_batch=16, hash_page=32, retry=FAST,
    )
    try:
        inj.set_faults(direction="s2c", drop_rate=0.03, truncate_rate=0.02)
        converged = False
        for _ in range(60):
            try:
                mgr.sync_once(inj.host, inj.port)
            except Exception:
                pass
            if local_eng.merkle_root() == remote_eng.merkle_root():
                converged = True
                break
        assert converged, (
            f"no convergence (dropped={inj.chunks_dropped})"
        )
        assert local_eng.snapshot() == remote_eng.snapshot()
    finally:
        inj.close()


# ----------------------------------------- device-mirror TREELEVEL serving


def test_treelevel_device_mirror_matches_native_host_tree(two_nodes):
    """The cluster callback serves TREELEVEL from the device-resident tree
    (promotion-chain corrected); its digests are bit-identical to the
    native server's host-tree fallback for every level."""
    from types import SimpleNamespace

    from merklekv_tpu.cluster.mirror import DeviceTreeMirror
    from merklekv_tpu.cluster.node import ClusterNode
    from merklekv_tpu.config import Config

    (eng, srv), (_, _) = two_nodes
    fill(eng, {f"dm{i:03d}": f"v{i}" for i in range(100)})

    with MerkleKVClient("127.0.0.1", srv.port) as c:
        native = {}
        lvl = 0
        while True:
            rows, n = c.tree_level(lvl, 0, 10**6)
            if not rows:
                break
            native[lvl] = rows
            lvl += 1
    assert n == 100 and len(native) >= 2

    node = ClusterNode(Config(), eng, srv)
    mirror = DeviceTreeMirror(eng)
    try:
        mirror.root_hex()  # force the device state build
        node._mirror = mirror
        node._replicator = SimpleNamespace(flush=lambda: None)
        for lvl, rows in native.items():
            resp = node._on_cluster_command(f"TREELEVEL {lvl} 0 1000000")
            assert resp is not None and resp.startswith(
                f"NODES {len(rows)} 100\r\n"
            )
            body = resp.split("\r\n")[1:-1]
            got = [tuple(line.split(" ")) for line in body]
            assert got == [(str(i), h) for i, h in rows], f"level {lvl}"
    finally:
        mirror.close()


# ------------------------------------- tombstone eviction (satellite)


def test_evicted_tombstone_still_blocks_resurrection(monkeypatch):
    """The tombstone-eviction resurrection hole: fill shards past the
    (shrunken) cap so the target deletion's tombstone is EVICTED, then LWW-
    sync against a stale peer still holding the old value — the delete must
    survive via the evicted-ts high-water mark."""
    monkeypatch.setenv("MKV_MAX_TOMBS_PER_SHARD", "4")
    a = NativeEngine("mem")
    monkeypatch.delenv("MKV_MAX_TOMBS_PER_SHARD")
    b = NativeEngine("mem")
    srv_b = NativeServer(b, "127.0.0.1", 0)
    srv_b.start()
    try:
        old_ts = 1_000
        a.set_with_ts(b"victim", b"old-value", old_ts)
        b.set_with_ts(b"victim", b"old-value", old_ts)  # stale peer copy
        a.delete(b"victim")  # tombstone at "now" >> old_ts
        assert a.tombstone_ts(b"victim") is not None
        # Flood deletions: every shard blows past the 4-tombstone cap, so
        # the victim's tombstone is evicted (oldest go first).
        for i in range(400):
            a.set(b"flood%03d" % i, b"x")
            a.delete(b"flood%03d" % i)
        assert a.tomb_evictions() > 0
        assert a.tombstone_ts(b"victim") is None, "tombstone must be evicted"

        # Engine-level: a stale LWW install below the evicted mark loses.
        assert not a.set_if_newer(b"victim", b"old-value", old_ts)
        # ...but a LIVE key is exempt from the mark: an update newer than
        # its entry must apply even with ts below the HWM — rejecting it
        # would pin the stale value, buying no deletion-stability.
        a.set_with_ts(b"livekey", b"v1", 500)
        assert a.set_if_newer(b"livekey", b"v2", 600)
        assert a.get(b"livekey") == b"v2"
        # A genuinely fresh write still wins (the mark is a floor, not a
        # freeze).
        import time as _t

        now = int(_t.time() * 1e9)
        assert a.set_if_newer(b"victim", b"fresh", now)
        a.delete(b"victim")

        # Cluster-level: multi-peer LWW sync against the stale peer must
        # not resurrect the deletion.
        mgr = SyncManager(a, device="cpu")
        mgr.sync_multi([f"127.0.0.1:{srv_b.port}"])
        assert a.get(b"victim") is None, "evicted deletion was resurrected"
    finally:
        srv_b.close()
        a.close()
        b.close()


def test_config_parses_walk_settings():
    from merklekv_tpu.config import Config

    cfg = Config.from_dict(
        {"anti_entropy": {"mode": "bisect", "bisect_threshold": 123}}
    )
    assert cfg.anti_entropy.mode == "bisect"
    assert cfg.anti_entropy.bisect_threshold == 123
    assert Config.from_dict({}).anti_entropy.mode == "auto"
    with pytest.raises(ValueError, match="mode"):
        Config.from_dict({"anti_entropy": {"mode": "zigzag"}})
